"""Config system: dataclass tree + YAML + dotted CLI overrides.

Equivalent of the reference's three config planes (SURVEY.md §5.6 / C18):
Hydra/OmegaConf trainer tree with CLI overrides (``ppo_stream_trainer.yaml``
composed over verl defaults, overridden in recipes), TOML for the
manager/fabric, env vars for point toggles. Hydra/OmegaConf are not in the
TPU image, so this is a self-contained equivalent: nested dataclasses are
the schema + defaults, a YAML file overlays them, and ``key.sub=value``
dotted CLI args overlay that (override order CLI > file > default, the
reference's order, config.rs:6).
"""

from __future__ import annotations

import dataclasses
import json
import typing
from dataclasses import dataclass, field
from typing import Any

from polyrl_tpu.rollout.autoscale import AutoscaleConfig
from polyrl_tpu.rollout.faults import FaultInjectionConfig
from polyrl_tpu.rollout.pool import PoolConfig
from polyrl_tpu.rollout.spotmarket import SpotMarketConfig
from polyrl_tpu.trainer.actor import ActorConfig
from polyrl_tpu.trainer.critic import CriticConfig
from polyrl_tpu.trainer.stream_trainer import TrainerConfig
from polyrl_tpu.transfer.agents import TransferConfig


@dataclass
class ModelSection:
    preset: str = "tiny"                  # any decoder.PRESETS key (tiny, qwen3-1.7b/8b, qwen2.5-0.5b/7b/32b, llama3-8b/70b)
    dtype: str = "bfloat16"
    # local HF checkpoint dir (config.json + safetensors): when set, the
    # architecture comes from the checkpoint's config.json and the weights
    # load pretrained instead of random-init (models/hf_loader.py)
    hf_path: str = ""
    # raw ModelConfig field overrides (vocab_size, num_layers, ...)
    overrides: dict = field(default_factory=dict)


@dataclass
class TokenizerSection:
    kind: str = "byte"                    # byte | hf
    name_or_path: str = ""                # hf repo/dir when kind == "hf"


@dataclass
class DataSection:
    train_path: str = "arithmetic"        # .jsonl/.parquet path, or "arithmetic"
    val_path: str = ""
    prompt_key: str = "prompt"
    shuffle: bool = True
    seed: int = 0
    arithmetic_size: int = 512            # synthetic task size


@dataclass
class RolloutSection:
    mode: str = "colocated"               # colocated | disaggregated
    backend: str = "cb"                   # cb (paged continuous batching) | step (bucketed)
    batch_buckets: tuple = ()             # step backend
    prompt_buckets: tuple = ()
    max_slots: int = 64                   # cb backend
    page_size: int = 64
    max_seq_len: int = 16384
    kv_cache_dtype: str = ""              # "" → model dtype
    # chunked prefill (cb backend): prompts longer than this prefill one
    # page-aligned chunk per engine iteration, interleaved with decode.
    # 0 = off (whole-prompt dispatches).
    prefill_chunk: int = 0
    # prompt-lookup speculative decoding (cb backend): N ngram-proposed
    # draft tokens verified per decode dispatch — up to N+1 tokens per
    # weight read, distribution-exact rejection sampling. 0 = off.
    spec_tokens: int = 0
    spec_rounds: int = 2                  # fused device-side rounds/dispatch
    # admission scheduler geometry (cb backend; ARCHITECTURE.md
    # "Group-shared prefill"): admit_wave = max admissions fused into one
    # batched prefill dispatch; admit_reorder_window = how many blocked
    # queue heads admission may skip past while forming a wave (0 =
    # strict FIFO head-of-line); group_share = prefill a GRPO group's
    # shared prompt once and batch-attach the siblings (False restores
    # per-request singleton suffix admission — the bench A/B baseline).
    admit_wave: int = 8
    admit_reorder_window: int = 8
    group_share: bool = True
    # shared-prefix decode attention (cb backend; ARCHITECTURE.md
    # "Shared-prefix decode attention"): decode dispatches with live GRPO
    # groups route through the two-phase grouped paged-attention kernel —
    # ONE HBM stream of the group's shared prompt KV serves all siblings
    # (phase 1), each slot's own suffix pages merge in via the flash LSE
    # (phase 2). False restores the per-slot kernel for every dispatch
    # (the --decode-attn A/B baseline; singletons always take that path).
    decode_group_share: bool = True
    # sibling-wait pre-ref expiry: how long a leader's pre-taken prefix
    # refs survive waiting for siblings that never arrive (dropped
    # groups, mis-sized hints) before the TTL sweep releases them
    group_preref_ttl_s: float = 30.0
    # KV memory plane (ARCHITECTURE.md "KV memory plane"): per-page
    # residency/lifetime ledger feeding the ``memory`` statusz section,
    # ``engine/kv_{hot,warm,cold}_page_frac`` gauges and HBM attribution.
    # False restores the pre-ledger engine, bit for bit.
    kv_ledger: bool = True
    # idle age (in decode dispatches since last touch) past which a
    # resident page counts as COLD (warm = a quarter of this)
    kv_cold_after_dispatches: int = 256
    # host-RAM KV spill tier (rollout/kvspill.py; ARCHITECTURE.md "KV
    # spill tier"): cold unreferenced published prefix-cache pages page
    # out of HBM into pinned host memory under watermark pressure and
    # restore on a prefix hit — sessions oversubscribe HBM instead of
    # losing their KV to eviction. Requires kv_ledger (candidate ranking
    # + reconciliation); kv_ledger=false disables the sweep entirely.
    kv_spill: bool = True
    # host-side capacity of the spill tier, in GB
    kv_spill_host_gb: float = 4.0
    # page-util watermarks with hysteresis: the sweep arms at >= high and
    # spills down toward low; the gap is what keeps demand restores from
    # re-arming the sweep page-by-page (spill/restore thrash)
    kv_spill_high_watermark: float = 0.92
    kv_spill_low_watermark: float = 0.80
    # engine-loop profiler (obs/engine_profile.py; ARCHITECTURE.md
    # "Engine-loop profiler"): per-iteration phase attribution of the CB
    # engine's loop wall behind the ``engine.loop`` statusz block,
    # ``engine/device_frac`` / ``engine/accounting_frac`` gauges and
    # tools/engine_report.py. False restores the pre-profiler engine,
    # bit for bit.
    loop_profile: bool = True
    # disaggregated plumbing (reference rollout_manager.{port,endpoint},
    # workers/config/rollout.py:95-101)
    manager_endpoint: str = ""            # "" → spawn the C++ manager locally
    manager_args: tuple = ()              # extra CLI args for the spawned manager
    # control-plane fault tolerance (ARCHITECTURE.md "Fault-tolerance
    # layers"): a locally spawned manager runs under a ManagerSupervisor
    # that respawns it with exponential backoff (base doubling to max) and
    # replays registered instances/senders/weight version via /reconcile
    manager_respawn_backoff_s: float = 0.5
    manager_respawn_backoff_max_s: float = 10.0
    # mid-stream transport failures re-issue only the unfinished rids, at
    # most resume_budget times per batch, waiting up to resume_wait_s each
    # time for the manager to come back; past the budget a colocated local
    # engine finishes the batch, else ControlPlaneDown surfaces
    resume_budget: int = 3
    resume_wait_s: float = 60.0
    # token-level continuous generation (ARCHITECTURE.md "Token-level
    # continuous generation"): aborts/preemptions/shutdowns flush partials
    # instead of dropping decoded tokens, the manager forwards per-token
    # progress, and a mid-stream resume re-issues only the SUFFIX
    # (prompt+salvaged re-prefilled, budget decremented) with the stitched
    # sequence re-decoding nothing. False reverts to from-token-0 resume.
    salvage_partials: bool = True
    # fault-injection harness (rollout/faults.py): kill-after-N-tokens,
    # chunk corruption, stalls, /drain triggers, and worst-moment manager
    # stream kills — for chaos tests and `bench.py --chaos`
    fault_injection: FaultInjectionConfig = field(
        default_factory=FaultInjectionConfig)
    transfer_streams: int = 4
    advertise_host: str = "127.0.0.1"
    # multi-NIC weight push (transfer/nic.py): >1 runs one sender agent per
    # CIDR-picked local interface and the manager partitions the pool across
    # them (reference 4 groups × 8 engines, config.toml:19-20)
    sender_groups: int = 1
    sender_nic_cidr: str = ""             # e.g. "10.128.0.0/16,10.129.0.0/16"
    groups_per_sender: int = 1            # manager-side instance sharding
    # hybrid colocated + remote: ALSO serve generation from an in-process
    # engine registered as a LOCAL (time-sliced) instance — the manager
    # aborts it after the balancer's local window and the engine yields its
    # KV HBM back to training (reference sglang_http_async_engine.py:102-113
    # + handlers.rs:500-513)
    colocated_local: bool = False
    # elastic pool (rollout/pool.py; ARCHITECTURE.md "Elastic pool"):
    # fleet membership lifecycle on top of the manager — scale-up join
    # gating, preemption drills, membership sweeps for /statusz, and the
    # progressive train<->rollout balance estimator window
    pool: PoolConfig = field(default_factory=PoolConfig)
    # closed-loop autoscaling (rollout/autoscale.py; ARCHITECTURE.md
    # "Closed-loop autoscaling & degradation tiers"): the policy loop
    # that ACTS on the balance trends + critpath bottleneck — PoolManager
    # add/drain under hysteresis, cooldowns, a fleet envelope, and a rate
    # limiter. Default OFF: the serial trainer stays bitwise pre-PR.
    autoscale: AutoscaleConfig = field(default_factory=AutoscaleConfig)
    # trace-driven spot-market chaos harness (rollout/spotmarket.py):
    # scripted capacity offers / preemption notices / no-notice kills
    # replayed against the pool — the controller's CapacityProvider
    spot_market: SpotMarketConfig = field(default_factory=SpotMarketConfig)


@dataclass
class ParallelSection:
    """Mesh axes for the trainer's GSPMD sharding (parallel/mesh.py). With
    every axis 1 and a single process, no mesh is built (single-chip path).
    Multi-host runs (jax.distributed via JAX_COORDINATOR_ADDRESS et al.)
    always build the mesh over the global device set."""
    dp: int = 1
    fsdp: int = 1                         # -1 absorbs remaining devices
    tp: int = 1
    sp: int = 1
    pp: int = 1                           # pipeline parallel (layer stages)
    pp_microbatches: int = 0              # GPipe microbatches (0 → 2·pp)
    ep: int = 1                           # expert parallel (MoE expert axis)
    # sequence-parallel attention flavor when sp > 1 (parallel/sequence.py):
    # ulysses (head all-to-all) | ring (KV ppermute) | dense (GSPMD decides)
    sp_mode: str = "ulysses"


@dataclass
class RewardSection:
    manager: str = "naive"
    custom_score_path: str = ""           # python file defining compute_score
    num_workers: int = 8
    # remote sandbox-service code execution (rewards/sandbox.py; reference
    # sandbox_fusion, reward.py:95-150). Empty url = local rlimit sandbox.
    sandbox_url: str = ""
    sandbox_max_concurrent: int = 64
    sandbox_timeout_s: float = 30.0
    sandbox_memory_limit_mb: int = 1024


@dataclass
class LoggingSection:
    backends: tuple = ("console",)        # console | jsonl | tensorboard
    path: str = ""                        # jsonl path / tensorboard dir


@dataclass
class ObsSection:
    """Observability knobs (ARCHITECTURE.md "Observability" + "Goodput &
    health plane"): span tracing with cross-process propagation + Perfetto
    export, the per-step manager /metrics scrape, the /statusz health
    exporter, and the anomaly flight recorder."""
    trace: bool = False                   # span tracer on/off
    trace_dir: str = ""                   # spans.jsonl + trace.json dump dir
    trace_buffer: int = 4096              # ring-buffer span capacity
    # wrap trainer phases in jax.profiler.TraceAnnotation so device traces
    # (trainer.profile_steps) line up with host spans
    jax_annotations: bool = False
    # live health plane: the trainer serves GET /statusz (shared schema
    # with the rollout server's route — obs/statusz.py). port 0 = ephemeral
    statusz: bool = False
    statusz_host: str = "127.0.0.1"
    statusz_port: int = 0
    # anomaly flight recorder (obs/recorder.py): EWMA/z-score detection
    # over step time + rollout throughput; dumps post-mortem bundles
    # (trace ring, last N step records, thread stacks, fault counters)
    # into recorder_dir on anomaly/crash/SIGTERM
    recorder: bool = False
    recorder_dir: str = ""                # "" -> next to logging.path
    recorder_keep_steps: int = 64         # step records per bundle
    recorder_z: float = 4.0               # z-score anomaly threshold
    recorder_warmup: int = 5              # steps before detection arms
    recorder_max_bundles: int = 4         # bundle budget per run
    # training health plane (obs/rlhealth.py): per-step RL-dynamics
    # ledger — training/* distributions + group diagnostics in every step
    # record, the /statusz training section, and training.json in
    # post-mortem bundles. Default ON (host-side numpy over arrays the
    # step already computed; no device work).
    rlhealth: bool = True
    rlhealth_tail: int = 64               # per-step rows kept for bundles
    rlhealth_group_rows: int = 64         # group-table rows per step


@dataclass
class RunConfig:
    model: ModelSection = field(default_factory=ModelSection)
    tokenizer: TokenizerSection = field(default_factory=TokenizerSection)
    data: DataSection = field(default_factory=DataSection)
    rollout: RolloutSection = field(default_factory=RolloutSection)
    # weight-push fabric supervision (transfer/agents.py TransferConfig;
    # ARCHITECTURE.md "Weight-fabric fault tolerance"): bandwidth-keyed
    # push deadlines, verify/resume toggle, retry budget + backoff, and
    # the transfer-plane fault injector — knobs echoed in step records
    # via the transfer/* gauges
    transfer: TransferConfig = field(default_factory=TransferConfig)
    parallel: ParallelSection = field(default_factory=ParallelSection)
    reward: RewardSection = field(default_factory=RewardSection)
    trainer: TrainerConfig = field(default_factory=TrainerConfig)
    actor: ActorConfig = field(default_factory=ActorConfig)
    critic: CriticConfig = field(default_factory=CriticConfig)
    logging: LoggingSection = field(default_factory=LoggingSection)
    obs: ObsSection = field(default_factory=ObsSection)


# -- dict ⇄ dataclass -------------------------------------------------------


def _build(cls, data: dict):
    """Construct dataclass ``cls`` from a (possibly partial) dict, recursing
    into dataclass-typed fields. Unknown keys raise (typo protection)."""
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise KeyError(f"unknown config keys for {cls.__name__}: {sorted(unknown)}")
    kwargs = {}
    for name, value in data.items():
        ftype = fields[name].type
        resolved = _resolve_type(cls, ftype)
        if dataclasses.is_dataclass(resolved) and isinstance(value, dict):
            kwargs[name] = _build(resolved, value)
        elif resolved is tuple or typing.get_origin(resolved) is tuple:
            kwargs[name] = tuple(value) if isinstance(value, (list, tuple)) else (value,)
        else:
            kwargs[name] = value
    return cls(**kwargs)


def _resolve_type(cls, ftype):
    """Field types are strings under ``from __future__ import annotations``."""
    if isinstance(ftype, str):
        hints = typing.get_type_hints(cls)
        # get_type_hints resolves the whole class; cache-free but configs are tiny
        for f in dataclasses.fields(cls):
            if f.type == ftype and f.name in hints:
                return hints[f.name]
        return str
    return ftype


def to_dict(cfg: Any) -> dict:
    d = dataclasses.asdict(cfg)

    def clean(x):
        if isinstance(x, dict):
            return {k: clean(v) for k, v in x.items()}
        if isinstance(x, tuple):
            return list(x)
        return x

    return clean(d)


# -- overrides --------------------------------------------------------------


def _coerce(text: str, current: Any) -> Any:
    """Parse a CLI string by the type of the value it replaces."""
    if isinstance(current, bool):
        if text.lower() in ("true", "1", "yes"):
            return True
        if text.lower() in ("false", "0", "no"):
            return False
        raise ValueError(f"not a bool: {text!r}")
    if isinstance(current, int) and not isinstance(current, bool):
        return int(text)
    if isinstance(current, float):
        return float(text)
    if isinstance(current, tuple):
        text = text.strip()
        if text[:1] == "[" and text[-1:] == "]":  # accept [8,16] list syntax
            text = text[1:-1]
        if not text:
            return ()
        items = [t.strip() for t in text.split(",") if t.strip()]
        conv = int if all(i.lstrip("-").isdigit() for i in items) else str
        return tuple(conv(i) for i in items)
    if isinstance(current, dict):
        return json.loads(text)
    if current is None:
        # str|None fields: "null" keeps None, anything else becomes str
        if text.lower() in ("null", "none", ""):
            return None
        for conv in (int, float):
            try:
                return conv(text)
            except ValueError:
                pass
        return text
    return text


def _set_path(obj: Any, parts: list[str], raw: str, full: str) -> Any:
    """Return ``obj`` with the dotted path set; frozen dataclasses are
    rebuilt via ``dataclasses.replace`` instead of mutated."""
    name = parts[0]
    if not dataclasses.is_dataclass(obj) or not hasattr(obj, name):
        raise KeyError(f"no config field {name!r} in {full!r}")
    cur = getattr(obj, name)
    new = _coerce(raw, cur) if len(parts) == 1 else _set_path(cur, parts[1:], raw, full)
    try:
        setattr(obj, name, new)
        return obj
    except dataclasses.FrozenInstanceError:
        return dataclasses.replace(obj, **{name: new})


def apply_overrides(cfg: RunConfig, overrides: list[str]) -> RunConfig:
    """``a.b.c=value`` dotted assignments, validated against the schema."""
    for ov in overrides:
        if "=" not in ov:
            raise ValueError(f"override must be key=value, got {ov!r}")
        key, _, raw = ov.partition("=")
        cfg = _set_path(cfg, key.strip().split("."), raw, key)
    return cfg


def load_config(path: str | None = None,
                overrides: list[str] | None = None) -> RunConfig:
    """YAML file (optional) overlaid on defaults, then dotted overrides.
    TrainerConfig validation (__post_init__ divisibility, the reference's
    main_stream.py:372-389 checks) re-runs on the final values."""
    data: dict = {}
    if path:
        import yaml

        with open(path) as f:
            data = yaml.safe_load(f) or {}
    cfg = _build(RunConfig, data)
    if overrides:
        cfg = apply_overrides(cfg, overrides)
    # re-validate trainer arithmetic after overrides mutated fields
    cfg.trainer.__post_init__()
    return cfg
