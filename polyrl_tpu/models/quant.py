"""Int8 weight-only quantization for the serving path.

The reference serves quantized models by delegating to SGLang's
quantization support (SGLang ``--quantization`` flag; PolyRL itself adds
nothing — the capability lives in the external engine, SURVEY.md §2.2
native-census row 1). Here the engine is first-party, so quantization is
first-party too: symmetric per-output-channel int8 weights with an f32
scale, dequantized inside the matmul epilogue.

Why this design on TPU:
- Decode is weight-HBM-bound (the whole param set streams through the MXU
  once per token). int8 storage halves that traffic → up to ~2× decode
  throughput before any kernel work.
- The int8→bf16 cast + per-channel scale multiply fuse into the XLA matmul
  as a prologue/epilogue — no separate dequantized copy of the weights
  ever materializes in HBM.
- Integer values in [-127, 127] are exactly representable in bf16 (8-bit
  mantissa covers ±256), so the cast itself is lossless; the only error is
  the quantization rounding, bounded by scale/2 per weight.
- It makes the 8B north-star model (Llama-3.1-8B, 16.06 GiB bf16) fit a
  16 GiB-HBM chip: int8 matmul weights + bf16 embeddings ≈ 8.6 GiB
  (8B_FEASIBILITY.md).

``QuantWeight`` is a registered pytree node, so quantized param trees flow
through ``jax.jit``, ``tree_map`` layer slicing, ``lax.scan``, device_put
sharding trees, and the engine's atomic weight swap exactly like plain
arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# layer-stacked matmul weights that get quantized ([L, in, out]);
# embed stays bf16 (it is a gather, not a matmul), norms/biases are tiny
QUANTIZED_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
# MoE expert weights ([L, E, in, out]) — the bulk of a MoE model's params;
# the tiny router ([L, d, E]) stays full precision (routing decisions are
# precision-sensitive and it is negligible HBM)
QUANTIZED_EXPERT_KEYS = ("we_gate", "we_up", "we_down")


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantWeight:
    """int8 weight + per-output-channel f32 scale.

    ``q``: int8, same shape as the original weight ([in, out] or stacked
    [L, in, out]). ``scale``: f32 with the contraction (input) axis
    reduced away ([out] or [L, out]); ``w ≈ q * scale`` broadcast over the
    input axis.
    """

    q: Any
    scale: Any

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    @property
    def shape(self):  # duck-type for code that sizes buffers off weights
        return self.q.shape


def quantize_tensor(w, contract_axis: int = -2) -> QuantWeight:
    """Symmetric per-output-channel int8: scale_j = max_i |w_ij| / 127.

    Works on numpy or jax arrays (dispatches on input type so host-side
    quantization of a received weight push never touches the device).
    ``contract_axis`` is the input/contraction axis that the scale reduces
    over (default -2: weights are [..., in, out]).
    """
    if isinstance(w, np.ndarray):
        wf = w.astype(np.float32)
        amax = np.max(np.abs(wf), axis=contract_axis)
        scale = (amax / 127.0 + 1e-12).astype(np.float32)
        q = np.clip(np.rint(wf / np.expand_dims(scale, contract_axis)),
                    -127, 127).astype(np.int8)
        return QuantWeight(q=q, scale=scale)
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=contract_axis)
    scale = (amax / 127.0 + 1e-12).astype(jnp.float32)
    q = jnp.clip(jnp.round(wf / jnp.expand_dims(scale, contract_axis)),
                 -127, 127).astype(jnp.int8)
    return QuantWeight(q=q, scale=scale)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class LoraWeight:
    """Low-rank adapter around a frozen base weight: ``w ≈ base +
    (alpha/r)·a@b`` (models/lora.py builds/merges these). ``base`` may
    itself be a QuantWeight — that composition IS QLoRA (int8 frozen base,
    trainable bf16 adapters). ``mm`` stops gradients at the base, so only
    a/b train; ``alpha`` rides the pytree aux data (static)."""

    base: Any
    a: Any  # [..., in, r]
    b: Any  # [..., r, out]
    alpha: float = 16.0

    def tree_flatten(self):
        return (self.base, self.a, self.b), self.alpha

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], children[2], aux)

    @property
    def shape(self):
        return self.base.shape


def mm(x, w):
    """``x @ w`` with transparent QuantWeight/LoraWeight dispatch
    (trace-time only — the isinstance checks cost nothing at runtime). The
    dequant epilogue runs in f32 and casts back to the activation dtype;
    XLA fuses it into the matmul."""
    if isinstance(w, LoraWeight):
        rank = w.a.shape[-1]
        base = jax.lax.stop_gradient(w.base)  # LoRA contract: base frozen
        delta = (x @ w.a.astype(x.dtype)) @ w.b.astype(x.dtype)
        return mm(x, base) + delta * (w.alpha / rank)
    if isinstance(w, QuantWeight):
        y = x @ w.q.astype(x.dtype)
        return (y.astype(jnp.float32) * w.scale).astype(x.dtype)
    return x @ w


def moe_mm(eq: str, x, w):
    """Expert-batched einsum (``..., out`` result, experts on result axis 1)
    with QuantWeight dispatch — the MoE expert projections' analogue of
    ``mm``. ``w.scale`` is [E, out] (contraction axis reduced away)."""
    if isinstance(w, QuantWeight):
        y = jnp.einsum(eq, x, w.q.astype(x.dtype))
        return (y.astype(jnp.float32)
                * w.scale[None, :, None, :]).astype(x.dtype)
    return jnp.einsum(eq, x, w)


def unembed(x, head, eq: str):
    """Logits head matmul (``jnp.einsum(eq, x, head)`` in f32) with
    QuantWeight dispatch; the per-vocab-channel scale multiplies the f32
    logits directly."""
    if isinstance(head, QuantWeight):
        logits = jnp.einsum(eq, x, head.q.astype(x.dtype),
                            preferred_element_type=jnp.float32)
        return logits * head.scale
    return jnp.einsum(eq, x, head, preferred_element_type=jnp.float32)


def quantize_params(params: dict) -> dict:
    """Quantize a decoder param tree's matmul weights: layer-stacked QKVO,
    dense MLP or MoE expert projections, and the untied lm_head;
    embed/norms/biases/router stay in model dtype. Accepts device (jax) or
    host (numpy) trees — each leaf quantizes with its own backend."""
    out = dict(params)
    layers = dict(params["layers"])
    for k in QUANTIZED_LAYER_KEYS + QUANTIZED_EXPERT_KEYS:
        if k in layers:  # dense vs MoE trees carry different MLP keys
            layers[k] = quantize_tensor(layers[k], contract_axis=-2)
    out["layers"] = layers
    if "lm_head" in params:
        out["lm_head"] = quantize_tensor(params["lm_head"], contract_axis=0)
    return out


def quant_param_specs(specs: dict) -> dict:
    """PartitionSpec tree matching ``quantize_params`` output: ``q`` keeps
    the weight's spec; ``scale`` keeps the spec with the contraction axis
    dropped (per-output-channel ⇒ sharded like the output dim)."""
    from jax.sharding import PartitionSpec as P

    out = dict(specs)
    layer = dict(specs["layers"])
    for k in QUANTIZED_LAYER_KEYS:
        if k not in layer:  # dense MLP keys absent on MoE models
            continue
        s = layer[k]  # P(layer, in, out)
        layer[k] = QuantWeight(q=s, scale=P(s[0], s[2]))
    for k in QUANTIZED_EXPERT_KEYS:
        if k not in layer:
            continue
        s = layer[k]  # P(layer, expert, in, out)
        layer[k] = QuantWeight(q=s, scale=P(s[0], s[1], s[3]))
    out["layers"] = layer
    if "lm_head" in specs:
        s = specs["lm_head"]  # P(in, out)
        out["lm_head"] = QuantWeight(q=s, scale=P(s[1]))
    return out


def init_quantized_params(rng: jax.Array, cfg) -> dict:
    """Random-init a decoder param tree directly in quantized form, leaf by
    leaf ON DEVICE — the bf16 8B tree (16 GiB) never exists anywhere, so an
    8B-int8 model can be built on a 16 GiB chip (bench path; real serving
    quantizes loaded checkpoints instead). Peak transient = one bf16 leaf
    (≤3.8 GiB for llama3-8b w_gate) + its int8 copy. Mirrors the structure
    of ``decoder.init_params`` (dense models only)."""
    if getattr(cfg, "num_experts", 0):
        raise NotImplementedError(
            "init_quantized_params supports dense models only; load a MoE "
            "checkpoint with quantize='int8' or quantize_params a loaded "
            "tree (experts quantize per-output-channel like the dense MLP)")
    hd = cfg.head_dim_
    d, f, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    hq, hkv = cfg.num_heads, cfg.num_kv_heads
    keys = jax.random.split(rng, 8)
    std = 0.02

    def _plain(key, shape):
        @jax.jit
        def make(k):
            return (jax.random.normal(k, shape, dtype=jnp.float32) * std
                    ).astype(cfg.dtype)
        return make(key)

    def _quant(key, *shape):
        @jax.jit
        def make(k):
            w = jax.random.normal(k, shape, dtype=jnp.float32) * std
            return quantize_tensor(w.astype(cfg.dtype), contract_axis=-2)
        qw = make(key)
        jax.block_until_ready(qw.q)
        return qw

    params = {
        "embed": _plain(keys[0], (cfg.vocab_size, d)),
        "final_norm": jnp.ones((d,), dtype=cfg.dtype),
        "layers": {
            "attn_norm": jnp.ones((L, d), dtype=cfg.dtype),
            "mlp_norm": jnp.ones((L, d), dtype=cfg.dtype),
            "wq": _quant(keys[1], L, d, hq * hd),
            "wk": _quant(keys[2], L, d, hkv * hd),
            "wv": _quant(keys[3], L, d, hkv * hd),
            "wo": _quant(keys[4], L, hq * hd, d),
            "w_gate": _quant(keys[5], L, d, f),
            "w_up": _quant(keys[6], L, d, f),
            "w_down": _quant(keys[7], L, f, d),
        },
    }
    if cfg.use_qk_norm:
        params["layers"]["q_norm"] = jnp.ones((L, hd), dtype=cfg.dtype)
        params["layers"]["k_norm"] = jnp.ones((L, hd), dtype=cfg.dtype)
    if cfg.attention_bias:
        params["layers"]["bq"] = jnp.zeros((L, hq * hd), dtype=cfg.dtype)
        params["layers"]["bk"] = jnp.zeros((L, hkv * hd), dtype=cfg.dtype)
        params["layers"]["bv"] = jnp.zeros((L, hkv * hd), dtype=cfg.dtype)
    if not cfg.tie_word_embeddings:
        @jax.jit
        def make_head(k):  # lm_head quantizes over the hidden (in) axis
            w = jax.random.normal(k, (d, cfg.vocab_size),
                                  dtype=jnp.float32) * std
            return quantize_tensor(w.astype(cfg.dtype), contract_axis=0)
        params["lm_head"] = make_head(jax.random.fold_in(rng, 99))
    return params
