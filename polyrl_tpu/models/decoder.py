"""Functional transformer decoder (Llama-3 / Qwen-3 families), TPU-first.

Replaces the reference's HF-transformers actor/critic modules wrapped in
FSDP (reference ``stream_fsdp_workers.py:284-302``) and SGLang's serving
model. One functional forward serves training (full-sequence, remat'd
scan-over-layers) and rollout (incremental decode against a KV cache).

Design choices (TPU rationale):
- Params are plain pytrees (nested dicts of jnp arrays); layer params are
  STACKED along a leading ``n_layers`` axis and the forward runs
  ``lax.scan`` over them — one compiled layer body regardless of depth
  (fast compile, XLA-friendly), with ``jax.checkpoint`` rematerialisation
  for the training path (HBM↔FLOPs trade, SURVEY.md §2.2 FSDP row).
- bf16 params/activations, f32 softmax/logits head.
- GQA + RoPE (llama3 frequency scaling supported), RMSNorm, SwiGLU,
  optional per-head QK-norm (Qwen3).
- ``param_specs`` returns a matching PartitionSpec tree: params shard over
  (fsdp, tp) — GSPMD inserts the all-gathers the reference got from FSDP
  + NCCL (SURVEY.md §2.4 mapping).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from polyrl_tpu.models.quant import mm, moe_mm, unembed
from polyrl_tpu.ops.attention import attention, causal_mask
from polyrl_tpu.parallel.mesh import DP, EP, FSDP, SP, TP


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """llama3-style NTK-by-parts frequency scaling (frozen → ModelConfig stays
    hashable for use as a jit static argument)."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_layers: int = 16
    num_heads: int = 16
    num_kv_heads: int = 8
    head_dim: int | None = None  # default hidden/heads
    rope_theta: float = 500000.0
    rope_scaling: RopeScaling | None = None
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    use_qk_norm: bool = False  # Qwen3
    attention_bias: bool = False  # Qwen2/2.5 family (qkv projection bias)
    max_position_embeddings: int = 131072
    # MoE (Qwen3-MoE / Mixtral-class): num_experts > 0 replaces every
    # layer's dense MLP with a routed mixture (softmax-over-all-experts
    # top-k routing, HF Qwen3MoeSparseMoeBlock semantics). Dispatch is
    # GShard-style fixed-capacity einsum (static shapes for the MXU);
    # moe_capacity_factor sizes the per-expert buffer — tokens routed past
    # capacity drop that expert contribution (standard GShard behavior).
    num_experts: int = 0
    num_experts_per_tok: int = 8
    moe_intermediate_size: int = 0
    norm_topk_prob: bool = True
    moe_capacity_factor: float = 2.0
    # tokens per routing group (GShard-style): capacity is per-group, so
    # dispatch/combine memory is O(N·E·k·cf/E·g)= linear in N instead of
    # O(N²). 0 → min(N, 512).
    moe_group_size: int = 0
    dtype: Any = jnp.bfloat16

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads


# -- presets ----------------------------------------------------------------

PRESETS: dict[str, ModelConfig] = {
    # test-size model for unit tests / CPU mesh dry runs
    "tiny": ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, rope_theta=10000.0, max_position_embeddings=512,
    ),
    # Llama-3.1-8B (HF config: meta-llama/Llama-3.1-8B)
    "llama3-8b": ModelConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=500000.0,
        rope_scaling=RopeScaling(factor=8.0, low_freq_factor=1.0,
                                 high_freq_factor=4.0,
                                 original_max_position_embeddings=8192),
    ),
    # Llama-3.2-1B (HF config: meta-llama/Llama-3.2-1B)
    "llama3.2-1b": ModelConfig(
        vocab_size=128256, hidden_size=2048, intermediate_size=8192,
        num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
        rope_theta=500000.0, tie_word_embeddings=True,
        rope_scaling=RopeScaling(factor=32.0, low_freq_factor=1.0,
                                 high_freq_factor=4.0,
                                 original_max_position_embeddings=8192),
    ),
    # Llama-3.2-3B (HF config: meta-llama/Llama-3.2-3B)
    "llama3.2-3b": ModelConfig(
        vocab_size=128256, hidden_size=3072, intermediate_size=8192,
        num_layers=28, num_heads=24, num_kv_heads=8, head_dim=128,
        rope_theta=500000.0, tie_word_embeddings=True,
        rope_scaling=RopeScaling(factor=32.0, low_freq_factor=1.0,
                                 high_freq_factor=4.0,
                                 original_max_position_embeddings=8192),
    ),
    # Qwen3-1.7B (the reference recipe model, run_async_grpo_pipeline.sh:17)
    "qwen3-1.7b": ModelConfig(
        vocab_size=151936, hidden_size=2048, intermediate_size=6144,
        num_layers=28, num_heads=16, num_kv_heads=8, head_dim=128,
        rope_theta=1000000.0, use_qk_norm=True, tie_word_embeddings=True,
    ),
    # Qwen3-8B
    "qwen3-8b": ModelConfig(
        vocab_size=151936, hidden_size=4096, intermediate_size=12288,
        num_layers=36, num_heads=32, num_kv_heads=8, head_dim=128,
        rope_theta=1000000.0, use_qk_norm=True,
    ),
    # Qwen2.5-0.5B (BASELINE config 1: GRPO on GSM8K)
    "qwen2.5-0.5b": ModelConfig(
        vocab_size=151936, hidden_size=896, intermediate_size=4864,
        num_layers=24, num_heads=14, num_kv_heads=2, rope_theta=1000000.0,
        attention_bias=True, tie_word_embeddings=True,
        max_position_embeddings=32768,
    ),
    # Qwen2.5-7B (BASELINE config 3's R1-Distill-Qwen-7B derives from the
    # MATH variant — see the distill preset below for its rope difference)
    "qwen2.5-7b": ModelConfig(
        vocab_size=152064, hidden_size=3584, intermediate_size=18944,
        num_layers=28, num_heads=28, num_kv_heads=4, rope_theta=1000000.0,
        attention_bias=True, max_position_embeddings=131072,
    ),
    # Qwen2.5-32B (BASELINE config 4: TP-sharded RLHF)
    "qwen2.5-32b": ModelConfig(
        vocab_size=152064, hidden_size=5120, intermediate_size=27648,
        num_layers=64, num_heads=40, num_kv_heads=8, rope_theta=1000000.0,
        attention_bias=True, max_position_embeddings=131072,
    ),
    # Llama-3.1-70B (BASELINE config 5: disaggregated multi-slice PPO)
    "llama3-70b": ModelConfig(
        vocab_size=128256, hidden_size=8192, intermediate_size=28672,
        num_layers=80, num_heads=64, num_kv_heads=8, rope_theta=500000.0,
        rope_scaling=RopeScaling(factor=8.0, low_freq_factor=1.0,
                                 high_freq_factor=4.0,
                                 original_max_position_embeddings=8192),
    ),
    # test-size MoE model (Qwen3-MoE architecture)
    "moe-tiny": ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, rope_theta=10000.0,
        max_position_embeddings=512, use_qk_norm=True,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=96,
    ),
    # Qwen3-30B-A3B (HF config: Qwen/Qwen3-30B-A3B — 128 experts, top-8)
    "qwen3-30b-a3b": ModelConfig(
        vocab_size=151936, hidden_size=2048, intermediate_size=6144,
        num_layers=48, num_heads=32, num_kv_heads=4, head_dim=128,
        rope_theta=1000000.0, use_qk_norm=True,
        num_experts=128, num_experts_per_tok=8, moe_intermediate_size=768,
    ),
    # Mixtral-8x7B (HF config: mistralai/Mixtral-8x7B-v0.1 — 8 experts,
    # top-2; Mixtral routing == softmax-all→top-k→renorm, see hf_loader)
    "mixtral-8x7b": ModelConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=1000000.0,
        rms_norm_eps=1e-5, max_position_embeddings=32768,
        num_experts=8, num_experts_per_tok=2, moe_intermediate_size=14336,
    ),
}

# DeepSeek-R1-Distill presets (BASELINE config 3 runs long-CoT GRPO on
# R1-Distill-Qwen-7B). The 32B/Llama-8B distills reuse their base
# architectures verbatim; the 7B is based on Qwen2.5-MATH-7B (rope_theta
# 10000, unlike base Qwen2.5-7B's 1e6) with the released distill config
# raising max positions to 131072 for its ~32k-token CoT traces.
PRESETS["deepseek-r1-distill-qwen-7b"] = dataclasses.replace(
    PRESETS["qwen2.5-7b"], rope_theta=10000.0)
PRESETS["deepseek-r1-distill-qwen-32b"] = PRESETS["qwen2.5-32b"]
PRESETS["deepseek-r1-distill-llama-8b"] = PRESETS["llama3-8b"]


def get_config(name: str, **overrides) -> ModelConfig:
    return dataclasses.replace(PRESETS[name], **overrides)


# -- init -------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: ModelConfig) -> dict:
    """Initialise stacked-layer params. Normal(0.02) like the HF default."""
    hd = cfg.head_dim_
    d, f, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    hq, hkv = cfg.num_heads, cfg.num_kv_heads
    keys = jax.random.split(rng, 8)
    std = 0.02

    def norm(key, *shape):
        return (jax.random.normal(key, shape, dtype=jnp.float32) * std).astype(cfg.dtype)

    if cfg.num_experts:
        fe = cfg.moe_intermediate_size
        mlp = {
            "router": norm(keys[5], L, d, cfg.num_experts),
            "we_gate": norm(keys[6], L, cfg.num_experts, d, fe),
            "we_up": norm(jax.random.fold_in(keys[6], 1), L,
                          cfg.num_experts, d, fe),
            "we_down": norm(keys[7], L, cfg.num_experts, fe, d),
        }
    else:
        mlp = {
            "w_gate": norm(keys[5], L, d, f),
            "w_up": norm(keys[6], L, d, f),
            "w_down": norm(keys[7], L, f, d),
        }
    params = {
        "embed": norm(keys[0], cfg.vocab_size, d),
        "final_norm": jnp.ones((d,), dtype=cfg.dtype),
        "layers": {
            "attn_norm": jnp.ones((L, d), dtype=cfg.dtype),
            "mlp_norm": jnp.ones((L, d), dtype=cfg.dtype),
            "wq": norm(keys[1], L, d, hq * hd),
            "wk": norm(keys[2], L, d, hkv * hd),
            "wv": norm(keys[3], L, d, hkv * hd),
            "wo": norm(keys[4], L, hq * hd, d),
            **mlp,
        },
    }
    if cfg.use_qk_norm:
        params["layers"]["q_norm"] = jnp.ones((L, hd), dtype=cfg.dtype)
        params["layers"]["k_norm"] = jnp.ones((L, hd), dtype=cfg.dtype)
    if cfg.attention_bias:
        params["layers"]["bq"] = jnp.zeros((L, hq * hd), dtype=cfg.dtype)
        params["layers"]["bk"] = jnp.zeros((L, hkv * hd), dtype=cfg.dtype)
        params["layers"]["bv"] = jnp.zeros((L, hkv * hd), dtype=cfg.dtype)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = norm(jax.random.fold_in(rng, 99), d, cfg.vocab_size)
    return params


def param_specs(cfg: ModelConfig) -> dict:
    """PartitionSpec tree matching ``init_params`` (fsdp × tp sharding)."""
    layer = {
        "attn_norm": P(None, None),
        "mlp_norm": P(None, None),
        "wq": P(None, FSDP, TP),
        "wk": P(None, FSDP, TP),
        "wv": P(None, FSDP, TP),
        "wo": P(None, TP, FSDP),
    }
    if cfg.num_experts:
        # experts shard over ep (the REAL expert axis — beyond the
        # reference's stubbed EP config, SURVEY.md §2.3); within each
        # expert the FFN shards like the dense MLP (fsdp × tp). GSPMD
        # derives the token dispatch/combine all-to-alls from these specs.
        layer.update({
            "router": P(None, FSDP, None),
            "we_gate": P(None, EP, FSDP, TP),
            "we_up": P(None, EP, FSDP, TP),
            "we_down": P(None, EP, TP, FSDP),
        })
    else:
        layer.update({
            "w_gate": P(None, FSDP, TP),
            "w_up": P(None, FSDP, TP),
            "w_down": P(None, TP, FSDP),
        })
    if cfg.use_qk_norm:
        layer["q_norm"] = P(None, None)
        layer["k_norm"] = P(None, None)
    if cfg.attention_bias:
        layer["bq"] = P(None, TP)
        layer["bk"] = P(None, TP)
        layer["bv"] = P(None, TP)
    specs = {
        "embed": P(TP, FSDP),
        "final_norm": P(None),
        "layers": layer,
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(FSDP, TP)
    return specs


# -- building blocks --------------------------------------------------------


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def _rope_freqs(cfg: ModelConfig) -> np.ndarray:
    hd = cfg.head_dim_
    freqs = 1.0 / (cfg.rope_theta ** (np.arange(0, hd, 2, dtype=np.float64) / hd))
    if cfg.rope_scaling:
        # llama3 NTK-by-parts frequency scaling (HF rope_scaling type="llama3")
        s = cfg.rope_scaling
        factor = s.factor
        low, high = s.low_freq_factor, s.high_freq_factor
        old_len = s.original_max_position_embeddings
        wavelen = 2 * np.pi / freqs
        ratio = old_len / wavelen
        smooth = np.clip((ratio - low) / (high - low), 0.0, 1.0)
        scaled = np.where(
            wavelen > old_len / low,  # low-frequency: fully scale
            freqs / factor,
            np.where(
                wavelen < old_len / high,  # high-frequency: keep
                freqs,
                (1 - smooth) * freqs / factor + smooth * freqs,
            ),
        )
        freqs = scaled
    return freqs.astype(np.float32)


def rope_cos_sin(cfg: ModelConfig, positions: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """positions [B, T] → (cos, sin) [B, T, hd/2] in f32."""
    freqs = jnp.asarray(_rope_freqs(cfg))
    angles = positions.astype(jnp.float32)[..., None] * freqs[None, None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x [B, T, H, D]; rotate-half convention (HF Llama/Qwen)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    cos = cos[:, :, None, :].astype(jnp.float32)
    sin = sin[:, :, None, :].astype(jnp.float32)
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out1 = x1f * cos - x2f * sin
    out2 = x2f * cos + x1f * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


# -- MoE MLP ----------------------------------------------------------------


def _moe_mlp(cfg: ModelConfig, x: jnp.ndarray, lp: dict,
             valid: jnp.ndarray | None = None) -> jnp.ndarray:
    """Routed mixture MLP on flattened tokens ``x`` [N, d] → [N, d].

    Routing follows HF Qwen3MoeSparseMoeBlock: softmax over ALL experts,
    top-k, optional renormalization of the k probabilities. Dispatch is
    GShard-style fixed capacity with TOKEN GROUPS: tokens are split into
    groups of ``moe_group_size`` and every expert processes
    ``C = ceil(k·g·capacity_factor / E)`` slots PER GROUP (static shapes —
    the TPU requirement). Grouping keeps dispatch/combine memory linear in
    N (the ungrouped [N, E, ceil(k·N·cf/E)] tensor is quadratic — a 4k-long
    MoE prefill would OOM), exactly GShard's motivation. Everything is
    batched einsums over the stacked expert weights [E, d, f] so the MXU
    sees large batched matmuls, not E small ones.

    ``valid`` [N] masks tokens out of routing entirely (bucket padding,
    inactive decode slots): without it, pad tokens — which all embed
    identically and therefore all route to the SAME experts — fill those
    experts' capacity ahead of later real tokens. Tokens routed to a full
    expert lose that expert's contribution (standard GShard dropping;
    capacity_factor ≥ E/k disables dropping exactly, which the HF-parity
    test uses)."""
    n, d = x.shape
    e, k = cfg.num_experts, cfg.num_experts_per_tok
    g = min(cfg.moe_group_size or 512, n)
    n_pad = -(-n // g) * g
    ng = n_pad // g

    if valid is None:
        valid = jnp.ones((n,), jnp.float32)
    else:
        valid = valid.astype(jnp.float32)
    x_p = jnp.pad(x, ((0, n_pad - n), (0, 0))) if n_pad != n else x
    valid = (jnp.pad(valid, (0, n_pad - n)) if n_pad != n else valid)

    router_logits = mm(x_p, lp["router"]).astype(jnp.float32)     # [Np, E]
    probs = jax.nn.softmax(router_logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, k)                        # [Np, k]
    if cfg.norm_topk_prob:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    cap = int(np.ceil(k * g * cfg.moe_capacity_factor / e))
    cap = max(1, min(cap, g))

    # slot assignment per group, token-major order (earlier tokens win
    # capacity; within a token its higher-probability choice wins — top_k
    # returns descending, so flattening [g, k] row-major preserves both)
    flat_e = top_i.reshape(ng, g * k)                             # [G, g·k]
    vk = jnp.repeat(valid.reshape(ng, g), k, axis=1)              # [G, g·k]
    e_onehot = jax.nn.one_hot(flat_e, e, dtype=jnp.float32) * vk[:, :, None]
    pos_in_e = jnp.cumsum(e_onehot, axis=1) - e_onehot            # [G, g·k, E]
    pos = jnp.take_along_axis(pos_in_e, flat_e[:, :, None], axis=2)[:, :, 0]
    keep = (pos < cap) * vk                                       # [G, g·k]
    cap_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap,
                            dtype=jnp.float32) * keep[:, :, None]

    # dispatch/combine [G, g, E, cap]: contract the k choices inside the
    # einsum — the [G, g, k, E, cap] product never materializes
    eo = e_onehot.reshape(ng, g, k, e)
    co = cap_oh.reshape(ng, g, k, cap)
    dispatch = jnp.einsum("gtke,gtkc->gtec", eo, co).astype(x.dtype)
    combine = jnp.einsum("gtke,gtkc,gtk->gtec", eo, co,
                         top_p.reshape(ng, g, k)).astype(jnp.float32)

    xg = x_p.reshape(ng, g, d)
    xe = jnp.einsum("gtd,gtec->gecd", xg, dispatch)               # [G, E, cap, d]
    gate = jax.nn.silu(moe_mm("gecd,edf->gecf", xe, lp["we_gate"]
                              ).astype(jnp.float32)).astype(x.dtype)
    up = moe_mm("gecd,edf->gecf", xe, lp["we_up"])
    ye = moe_mm("gecf,efd->gecd", gate * up, lp["we_down"])       # [G, E, cap, d]
    out = jnp.einsum("gecd,gtec->gtd", ye.astype(jnp.float32), combine)
    return out.reshape(n_pad, d)[:n].astype(x.dtype)


def _mlp_block(cfg: ModelConfig, h: jnp.ndarray, lp: dict,
               valid: jnp.ndarray | None = None) -> jnp.ndarray:
    """Post-norm MLP: dense SwiGLU, or the routed mixture when the config
    is MoE. ``h`` is [..., d]; MoE flattens leading dims into one token
    axis (routing is per-token, layout-independent). ``valid`` matches
    ``h``'s leading dims and keeps padding/inactive tokens from consuming
    expert capacity."""
    if cfg.num_experts:
        shape = h.shape
        v = valid.reshape(-1) if valid is not None else None
        return _moe_mlp(cfg, h.reshape(-1, shape[-1]), lp, v).reshape(shape)
    gate = jax.nn.silu(mm(h, lp["w_gate"]).astype(jnp.float32)).astype(h.dtype)
    return mm(gate * mm(h, lp["w_up"]), lp["w_down"])


# -- forward ----------------------------------------------------------------


def _layer_forward(cfg, x, lp, cos, sin, mask, layer_cache, attn_fn=None,
                   token_valid=None):
    """One decoder layer. layer_cache: None or (k_cache, v_cache) [B, S, Hkv, D]
    already containing past KV; this layer writes its new KV at write_idx.
    ``attn_fn``: optional sequence-parallel attention (Ulysses/ring,
    polyrl_tpu.parallel.sequence) used on the no-cache (training) path."""
    b, t, d = x.shape
    hq, hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_

    h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q, k, v = mm(h, lp["wq"]), mm(h, lp["wk"]), mm(h, lp["wv"])
    if cfg.attention_bias:  # Qwen2/2.5 family
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(b, t, hq, hd)
    k = k.reshape(b, t, hkv, hd)
    v = v.reshape(b, t, hkv, hd)
    if cfg.use_qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if layer_cache is not None:
        k_cache, v_cache, write_idx = layer_cache
        k_full = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype), (0, write_idx, 0, 0))
        v_full = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype), (0, write_idx, 0, 0))
        attn_out = attention(q, k_full, v_full, mask=mask)
        new_cache = (k_full, v_full)
    elif attn_fn is not None:
        attn_out = attn_fn(q, k, v)  # SP impl applies causal+pad internally
        new_cache = None
    else:
        attn_out = attention(q, k, v, mask=mask)
        new_cache = None

    attn_out = mm(attn_out.reshape(b, t, hq * hd), lp["wo"])
    x = x + attn_out

    h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    x = x + _mlp_block(cfg, h, lp, token_valid)
    return x, new_cache


def forward(
    params: dict,
    cfg: ModelConfig,
    input_ids: jnp.ndarray,          # [B, T]
    positions: jnp.ndarray,          # [B, T] absolute positions (left-pad aware)
    attn_mask: jnp.ndarray,          # [B, Tk] 1=valid token (Tk = T, or cache len when cache given)
    cache: tuple | None = None,      # (k, v) each [L, B, S, Hkv, D]
    write_idx: int | jnp.ndarray = 0,
    remat: bool = False,
    attn_fn=None,                    # SP attention (parallel.sequence), no-cache path only
    logits_for: jnp.ndarray | None = None,  # [B] int32 — unembed only this position
    layers_fn=None,                  # pipeline-parallel layer stack (parallel.pipeline)
) -> tuple[jnp.ndarray, tuple | None]:
    """Returns (logits [B, T, V] float32 — or [B, V] when ``logits_for`` is
    given — and new_cache or None).

    Without cache: full-sequence causal forward (training / prefill-scoring).
    With cache: attends over the cache buffer [B, S]; the current chunk's KV
    is written at ``write_idx``; ``attn_mask`` must be [B, S] marking valid
    cache slots INCLUDING the chunk being written.
    """
    b, t = input_ids.shape
    x = params["embed"][input_ids]  # gather; sharded over tp on vocab dim

    cos, sin = rope_cos_sin(cfg, positions)

    if cache is None:
        if attn_fn is not None or layers_fn is not None:
            # SP attention / the pipeline build causal+pad masks internally
            mask = None
        else:
            # causal within the chunk + padding mask
            cm = causal_mask(t, t)  # [T, T]
            mask = cm[None, None, :, :] & (attn_mask[:, None, None, :] > 0)
    else:
        # left-padded layout: cache slot order == temporal order, so the
        # causal constraint is expressed in slot indices, not positions.
        s = cache[0].shape[2]
        kv_pos = jnp.arange(s)[None, None, None, :]
        slot_written = kv_pos <= (write_idx + t - 1)  # slots at/below the chunk
        causal = kv_pos <= (write_idx + jnp.arange(t)[None, None, :, None])
        mask = causal & slot_written & (attn_mask[:, None, None, :] > 0)

    layers = params["layers"]

    if cache is None:
        if layers_fn is not None:
            # pipeline-parallel stack (parallel.pipeline): the pipeline owns
            # microbatching, masking, and remat for the layer loop
            x = layers_fn(layers, x, cos, sin, attn_mask)
            new_cache = None
        else:
            layer_attn = None
            if attn_fn is not None:
                layer_attn = lambda q, k, v: attn_fn(q, k, v, attn_mask)  # noqa: E731
            tok_valid = attn_mask > 0  # [B, T] — MoE routing skips pads

            def body(x, lp):
                x, _ = _layer_forward(cfg, x, lp, cos, sin, mask, None,
                                      attn_fn=layer_attn,
                                      token_valid=tok_valid)
                return x, None
            if remat:
                body = jax.checkpoint(body)
            x, _ = jax.lax.scan(body, x, layers)
            new_cache = None
    else:
        # UNROLLED layer loop with single-token in-place cache writes.
        # A scan would force the cache through xs/ys (fresh stacked
        # allocations: full [L, B, S] rewrite per decode step) or through
        # the carry with dynamic layer indexing (full layer-slice copy per
        # layer). Static layer indices turn the write into a [B, T]-token
        # dynamic-update-slice and the read into a lazily-fused view —
        # decode becomes weights+KV-read bound, the HBM floor.
        k_cache, v_cache = cache
        n_layers = k_cache.shape[0]
        b = x.shape[0]
        t_chunk = x.shape[1]
        hq, hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
        # chunk validity from the cache-slot mask (the chunk occupies slots
        # [write_idx, write_idx+t)): keeps MoE routing off padded tokens
        chunk_valid = jax.lax.dynamic_slice_in_dim(
            attn_mask, write_idx, t_chunk, axis=1) > 0
        for l in range(n_layers):
            lp = jax.tree_util.tree_map(lambda a: a[l], layers)
            h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            q, k, v = mm(h, lp["wq"]), mm(h, lp["wk"]), mm(h, lp["wv"])
            if cfg.attention_bias:
                q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
            q = q.reshape(b, t_chunk, hq, hd)
            k = k.reshape(b, t_chunk, hkv, hd)
            v = v.reshape(b, t_chunk, hkv, hd)
            if cfg.use_qk_norm:
                q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
                k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            k_cache = jax.lax.dynamic_update_slice(
                k_cache, k[None].astype(k_cache.dtype), (l, 0, write_idx, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                v_cache, v[None].astype(v_cache.dtype), (l, 0, write_idx, 0, 0))
            attn_out = attention(q, k_cache[l], v_cache[l], mask=mask)
            x = x + mm(attn_out.reshape(b, t_chunk, hq * hd), lp["wo"])
            h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
            x = x + _mlp_block(cfg, h, lp, chunk_valid)
        new_cache = (k_cache, v_cache)

    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    if logits_for is not None:
        # unembed only one position per row: prefill needs just the last
        # real token's logits, and [B, T, V] f32 for a long chunk is the
        # dominant HBM transient (e.g. 4k x 152k f32 = 2.5 GB per prompt)
        x = jnp.take_along_axis(x, logits_for[:, None, None], axis=1)[:, 0]
        return unembed(x, head, "bd,dv->bv"), new_cache
    return unembed(x, head, "btd,dv->btv"), new_cache


# -- paged KV (continuous batching) -----------------------------------------


def make_paged_pools(cfg: ModelConfig, num_pages: int, page_size: int,
                     dtype=None) -> tuple:
    """Paged KV pool: (k, v), each a PER-LAYER tuple of
    [Hkv, num_pages, page_size, D] arrays.

    Head-major layout: each layer's pool is exactly the
    [num_kv_heads, total_pages, page_size, head_dim] shape the TPU paged
    decode kernel streams (one (kv_head, page-block) DMA per grid step), so
    the hot loop never transposes the multi-GB pool. Per-layer arrays, not
    one stacked [L, ...]: the decode step's KV scatter prefers a physical
    layout the stacked form lets XLA actually pick — which then forces a
    full-pool copy per scan iteration to satisfy the attention kernel's
    standard-layout operand (observed: 2×3.5 GB temps, OOM at 128 slots).
    Separate 4-D buffers keep scatter and kernel in layout agreement.

    Page 0 is reserved as the null page — inactive slots and padding scatter
    their garbage KV there so every decode step has uniform static shapes
    (the TPU answer to SGLang's paged allocator, SURVEY.md §2.2 row 1)."""
    dtype = dtype or cfg.dtype
    shape = (cfg.num_kv_heads, num_pages, page_size, cfg.head_dim_)
    return (tuple(jnp.zeros(shape, dtype=dtype) for _ in range(cfg.num_layers)),
            tuple(jnp.zeros(shape, dtype=dtype) for _ in range(cfg.num_layers)))


def _scatter_token_kv(pool, write_page, write_off, upd):
    """Scatter one token's KV per slot into ``pool`` [Hkv, N, ps, D];
    ``upd`` is [S, Hkv, D]. Written as a ROW scatter in the flattened
    [Hkv·N·ps, D] view: the update window is then the minor-most dim alone,
    so XLA's layout assignment keeps the pool in standard layout — the
    4-D form's split window (Hkv major + D minor) made layout assignment
    pick a permuted physical layout, and the attention kernel's
    standard-layout operand constraint then forced a full-pool copy every
    decode iteration."""
    hkv, n, ps, d = pool.shape
    s = write_page.shape[0]
    flat = pool.reshape(hkv * n * ps, d)
    head_off = jnp.arange(hkv, dtype=jnp.int32)[:, None] * (n * ps)
    idx = (head_off + (write_page * ps + write_off)[None, :]).reshape(-1)
    flat = flat.at[idx].set(
        upd.transpose(1, 0, 2).reshape(hkv * s, d).astype(pool.dtype))
    return flat.reshape(hkv, n, ps, d)


def _scatter_pages_kv(pool, page_ids, upd):
    """Scatter whole pages into ``pool`` [Hkv, N, ps, D]; ``upd`` is
    [Hkv, n_pg, ps, D]. Same flat-row trick as ``_scatter_token_kv``
    ([Hkv·N, ps·D] rows) to keep the pool in standard layout."""
    hkv, n, ps, d = pool.shape
    npg = page_ids.shape[0]
    flat = pool.reshape(hkv * n, ps * d)
    idx = (jnp.arange(hkv, dtype=jnp.int32)[:, None] * n
           + page_ids[None, :].astype(jnp.int32)).reshape(-1)
    flat = flat.at[idx].set(upd.reshape(hkv * npg, ps * d).astype(pool.dtype))
    return flat.reshape(hkv, n, ps, d)


def forward_paged_decode(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,      # [S] int32 — one new token per slot
    positions: jnp.ndarray,   # [S] int32 — absolute position of that token
    pools: tuple,             # (k, v): per-layer tuples of [Hkv, N, page, D]
    page_table: jnp.ndarray,  # [S, P] int32
    seq_lens: jnp.ndarray,    # [S] int32 tokens already in cache (== positions)
    attn_fn=None,
    active: jnp.ndarray | None = None,  # [S] bool — mask KV writes
    kv_write_fn=None,  # TP override (ops.paged_attention.make_tp_paged_kv_write)
) -> tuple[jnp.ndarray, tuple]:
    """One decode step for every slot at once: write the new token's KV into
    each slot's current page, then paged-attend over [0, seq_len]. Returns
    (logits [S, V] f32, updated pools). Static shapes regardless of the mix
    of live requests — the continuous-batching hot loop.

    ``active`` routes INACTIVE slots' writes to the null page 0: a finished
    slot's pages return to the allocator while its device page_table row is
    still stale, so an unmasked write would corrupt whichever request
    reuses those pages (one garbage KV token per later dispatch).

    ``attn_fn(q, k_pool, v_pool, page_table, lens)`` is the decode
    attention seam: the TP engine shard_maps the Pallas kernel through it,
    and the shared-prefix grouped decode path (CBEngine with live GRPO
    groups) passes a closure over the dispatch's group tables that routes
    into ``ops.paged_attention.grouped_paged_attention`` — this forward
    stays group-agnostic; the per-slot ``page_table`` contract is
    unchanged (grouping only changes the kernel's HBM read pattern)."""
    from polyrl_tpu.ops.paged_attention import paged_attention, paged_kv_write

    attn_fn = attn_fn or paged_attention
    kv_write_fn = kv_write_fn or paged_kv_write
    s = tokens.shape[0]
    hq, hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    page_size = pools[0][0].shape[2]

    x = params["embed"][tokens]  # [S, d]
    cos, sin = rope_cos_sin(cfg, positions[:, None])  # [S, 1, hd/2]
    write_page = page_table[jnp.arange(s), seq_lens // page_size]  # [S]
    write_off = seq_lens % page_size
    if active is not None:
        write_page = jnp.where(active, write_page, 0)
        write_off = jnp.where(active, write_off, 0)
    attn_lens = seq_lens + 1  # include the token written this step

    layers = params["layers"]

    # UNROLLED layer loop, static layer indices: pool writes are per-token
    # scatters and pool reads are the per-layer buffers directly. A scan
    # would copy entire pool layers per step (ys restacking or dynamic layer
    # slicing) — catastrophic when the pool IS the whole KV memory.
    k_pools, v_pools = list(pools[0]), list(pools[1])
    n_layers = len(k_pools)
    for l in range(n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[l], layers)
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = mm(h, lp["wq"]), mm(h, lp["wk"]), mm(h, lp["wv"])
        if cfg.attention_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(s, 1, hq, hd)
        k = k.reshape(s, 1, hkv, hd)
        v = v.reshape(s, 1, hkv, hd)
        if cfg.use_qk_norm:
            q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
            k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # fused K+V Pallas write on TPU (XLA row-scatter elsewhere): the
        # scatter lowers to a serialized per-row loop on TPU and was the
        # dominant cost of the whole decode step (2 x n_layers x k fused
        # steps of S*Hkv-row scatters per dispatch)
        k_pools[l], v_pools[l] = kv_write_fn(
            k_pools[l], v_pools[l], write_page, write_off, k[:, 0], v[:, 0])
        attn_out = attn_fn(q[:, 0], k_pools[l], v_pools[l], page_table,
                           attn_lens)  # [S, Hq, D]
        x = x + mm(attn_out.reshape(s, hq * hd), lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        # inactive slots route nowhere (their pad rows would otherwise fill
        # the experts real slots route to)
        x = x + _mlp_block(cfg, h, lp, active)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    return unembed(x, head, "sd,dv->sv"), (tuple(k_pools), tuple(v_pools))


def prefill_into_pages(
    params: dict,
    cfg: ModelConfig,
    ids: jnp.ndarray,         # [pb] int32 right-padded prompt
    prompt_len: jnp.ndarray,  # scalar int32
    pools: tuple,
    page_ids: jnp.ndarray,    # [pb // page_size] int32 (0-padded past prompt)
) -> tuple[tuple, jnp.ndarray]:
    """Prefill one prompt and scatter its KV into the slot's pages. Returns
    (updated pools, last-token logits [V] f32). Padding positions write into
    the null page / the tail of the last real page — never attended (masking
    is by seq_len everywhere)."""
    page_size = pools[0][0].shape[2]
    pb = ids.shape[0]
    n_pg = pb // page_size
    layers = cfg.num_layers
    hkv, hd = cfg.num_kv_heads, cfg.head_dim_

    mask = (jnp.arange(pb) < prompt_len).astype(jnp.float32)[None]
    positions = jnp.arange(pb, dtype=jnp.int32)[None]
    cache = make_cache(cfg, 1, pb, dtype=pools[0][0].dtype)
    last_logits, (k_new, v_new) = forward(
        params, cfg, ids[None], positions, mask, cache=cache, write_idx=0,
        logits_for=jnp.maximum(prompt_len - 1, 0)[None])

    # [L, pb, hkv, hd] → per layer [hkv, n_pg, page, hd] (head-major pools)
    k_r = k_new[:, 0].reshape(layers, n_pg, page_size, hkv, hd).transpose(0, 3, 1, 2, 4)
    v_r = v_new[:, 0].reshape(layers, n_pg, page_size, hkv, hd).transpose(0, 3, 1, 2, 4)
    k_pools = tuple(_scatter_pages_kv(pools[0][l], page_ids, k_r[l])
                    for l in range(layers))
    v_pools = tuple(_scatter_pages_kv(pools[1][l], page_ids, v_r[l])
                    for l in range(layers))
    return (k_pools, v_pools), last_logits[0]


def prefill_batch_into_pages(
    params: dict,
    cfg: ModelConfig,
    ids: jnp.ndarray,          # [B, pb] int32 right-padded prompts
    prompt_lens: jnp.ndarray,  # [B] int32
    pools: tuple,
    page_ids: jnp.ndarray,     # [B, pb // page_size] int32
) -> tuple[tuple, jnp.ndarray]:
    """Batched admission prefill: B prompts in ONE dispatch. Dispatch count
    is the admission bottleneck on dispatch-latency-bound links (and still
    wins on real hardware: one [B, pb] forward beats B serialized [pb]
    forwards). Returns (updated pools, last-token logits [B, V] f32).
    Duplicate page rows (wave padding repeats a real request) write the
    same content twice — benign."""
    page_size = pools[0][0].shape[2]
    b, pb = ids.shape
    n_pg = pb // page_size
    layers = cfg.num_layers
    hkv, hd = cfg.num_kv_heads, cfg.head_dim_

    mask = (jnp.arange(pb)[None, :] < prompt_lens[:, None]).astype(jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(pb, dtype=jnp.int32), (b, pb))
    cache = make_cache(cfg, b, pb, dtype=pools[0][0].dtype)
    last_logits, (k_new, v_new) = forward(
        params, cfg, ids, positions, mask, cache=cache, write_idx=0,
        logits_for=jnp.maximum(prompt_lens - 1, 0))

    # [L, B, pb, hkv, hd] → per layer [hkv, B·n_pg, page, hd]
    k_r = k_new.reshape(layers, b * n_pg, page_size, hkv, hd).transpose(0, 3, 1, 2, 4)
    v_r = v_new.reshape(layers, b * n_pg, page_size, hkv, hd).transpose(0, 3, 1, 2, 4)
    flat_pages = page_ids.reshape(-1)
    k_pools = tuple(_scatter_pages_kv(pools[0][l], flat_pages, k_r[l])
                    for l in range(layers))
    v_pools = tuple(_scatter_pages_kv(pools[1][l], flat_pages, v_r[l])
                    for l in range(layers))
    return (k_pools, v_pools), last_logits


def prefill_suffix_into_pages(
    params: dict,
    cfg: ModelConfig,
    ids: jnp.ndarray,             # [pb] int32 right-padded suffix tokens
    suffix_len: jnp.ndarray,      # scalar int32 — real suffix tokens
    prefix_len: jnp.ndarray,      # scalar int32 — cached tokens (whole pages)
    pools: tuple,
    prefix_page_ids: jnp.ndarray, # [n_prefix_pg] int32 (0/null-padded tail)
    page_ids: jnp.ndarray,        # [pb // page_size] int32 suffix pages
) -> tuple[tuple, jnp.ndarray]:
    """Prefix-cache prefill: compute KV only for the suffix while attending
    over the cached prefix pages (the compute-skip that makes page-granular
    prefix reuse worthwhile — the TPU analogue of SGLang RadixAttention
    prefix hits, SURVEY.md §2.2 native-census row 1).

    The prefix occupies whole pages (``prefix_len`` ≤
    ``n_prefix_pg·page_size``, padded entries null); suffix KV is scattered
    into ``page_ids``. Returns (updated pools, last-token logits [V] f32).
    """
    page_size = pools[0][0].shape[2]
    pb = ids.shape[0]
    n_pg = pb // page_size
    n_prefix_pg = prefix_page_ids.shape[0]
    prefix_cap = n_prefix_pg * page_size
    layers = cfg.num_layers
    hkv, hd = cfg.num_kv_heads, cfg.head_dim_

    # dense scratch cache: [prefix_cap | suffix chunk]
    s_total = prefix_cap + pb
    cache = make_cache(cfg, 1, s_total, dtype=pools[0][0].dtype)
    # per layer [hkv, n_pre, page, hd] → dense [L, prefix_cap, hkv, hd]
    k_pre = jnp.stack([pools[0][l][:, prefix_page_ids] for l in range(layers)])
    v_pre = jnp.stack([pools[1][l][:, prefix_page_ids] for l in range(layers)])
    k_pre = k_pre.transpose(0, 2, 3, 1, 4)
    v_pre = v_pre.transpose(0, 2, 3, 1, 4)
    cache = (
        cache[0].at[:, 0, :prefix_cap].set(
            k_pre.reshape(layers, prefix_cap, hkv, hd)),
        cache[1].at[:, 0, :prefix_cap].set(
            v_pre.reshape(layers, prefix_cap, hkv, hd)),
    )
    # slot layout: prefix occupies [0, prefix_len); the chunk writes at
    # write_idx=prefix_len so slot order stays temporal (padded prefix tail
    # slots get overwritten by the chunk — they were masked anyway)
    positions = (prefix_len + jnp.arange(pb, dtype=jnp.int32))[None]
    slot_idx = jnp.arange(s_total)
    valid = ((slot_idx < prefix_len)
             | ((slot_idx >= prefix_len) & (slot_idx < prefix_len + suffix_len)))
    last_logits, (k_all, v_all) = forward(
        params, cfg, ids[None], positions, valid[None].astype(jnp.float32),
        cache=cache, write_idx=prefix_len,
        logits_for=jnp.maximum(suffix_len - 1, 0)[None])

    k_sfx = jax.lax.dynamic_slice_in_dim(k_all[:, 0], prefix_len, pb, axis=1)
    v_sfx = jax.lax.dynamic_slice_in_dim(v_all[:, 0], prefix_len, pb, axis=1)
    k_r = k_sfx.reshape(layers, n_pg, page_size, hkv, hd).transpose(0, 3, 1, 2, 4)
    v_r = v_sfx.reshape(layers, n_pg, page_size, hkv, hd).transpose(0, 3, 1, 2, 4)
    k_pools = tuple(_scatter_pages_kv(pools[0][l], page_ids, k_r[l])
                    for l in range(layers))
    v_pools = tuple(_scatter_pages_kv(pools[1][l], page_ids, v_r[l])
                    for l in range(layers))
    return (k_pools, v_pools), last_logits[0]


def prefill_suffix_batch_into_pages(
    params: dict,
    cfg: ModelConfig,
    ids: jnp.ndarray,             # [B, pb] int32 right-padded suffix tokens
    suffix_lens: jnp.ndarray,     # [B] int32 — real suffix tokens per row
    prefix_len: jnp.ndarray,      # scalar int32 — cached tokens, UNIFORM
    pools: tuple,
    prefix_page_ids: jnp.ndarray, # [B, n_prefix_pg] int32 (null-padded tail)
    page_ids: jnp.ndarray,        # [B, pb // page_size] int32 suffix pages
) -> tuple[tuple, jnp.ndarray]:
    """Batched prefix-cache prefill: B suffixes in ONE dispatch, each
    attending over its own cached prefix pages — the group-shared-prefill
    sibling attach. GRPO's G-samples-per-prompt means the G−1 siblings of a
    published prompt arrive together with IDENTICAL prefix length; admitting
    them as G−1 serialized singleton suffix dispatches made the admission
    dispatch count linear in the rollout count (DualKV's exact target
    workload). Requires a UNIFORM ``prefix_len`` across rows (the scratch
    cache's write offset is one traced scalar); rows may differ in suffix
    content/length and prefix pages. Returns (updated pools, last-token
    logits [B, V] f32)."""
    page_size = pools[0][0].shape[2]
    b, pb = ids.shape
    n_pg = pb // page_size
    n_prefix_pg = prefix_page_ids.shape[1]
    prefix_cap = n_prefix_pg * page_size
    layers = cfg.num_layers
    hkv, hd = cfg.num_kv_heads, cfg.head_dim_

    # dense scratch cache per row: [prefix_cap | suffix chunk]
    s_total = prefix_cap + pb
    cache = make_cache(cfg, b, s_total, dtype=pools[0][0].dtype)
    # per layer [hkv, B, n_pre, page, hd] → dense [L, B, prefix_cap, hkv, hd]
    k_pre = jnp.stack([pools[0][l][:, prefix_page_ids] for l in range(layers)])
    v_pre = jnp.stack([pools[1][l][:, prefix_page_ids] for l in range(layers)])
    k_pre = k_pre.transpose(0, 2, 3, 4, 1, 5)
    v_pre = v_pre.transpose(0, 2, 3, 4, 1, 5)
    cache = (
        cache[0].at[:, :, :prefix_cap].set(
            k_pre.reshape(layers, b, prefix_cap, hkv, hd)),
        cache[1].at[:, :, :prefix_cap].set(
            v_pre.reshape(layers, b, prefix_cap, hkv, hd)),
    )
    positions = jnp.broadcast_to(
        prefix_len + jnp.arange(pb, dtype=jnp.int32), (b, pb))
    slot_idx = jnp.arange(s_total)
    valid = ((slot_idx[None, :] < prefix_len)
             | ((slot_idx[None, :] >= prefix_len)
                & (slot_idx[None, :] < prefix_len + suffix_lens[:, None])))
    last_logits, (k_all, v_all) = forward(
        params, cfg, ids, positions, valid.astype(jnp.float32),
        cache=cache, write_idx=prefix_len,
        logits_for=jnp.maximum(suffix_lens - 1, 0))

    k_sfx = jax.lax.dynamic_slice_in_dim(k_all, prefix_len, pb, axis=2)
    v_sfx = jax.lax.dynamic_slice_in_dim(v_all, prefix_len, pb, axis=2)
    # [L, B, pb, hkv, hd] → per layer [hkv, B·n_pg, page, hd]
    k_r = k_sfx.reshape(layers, b * n_pg, page_size, hkv, hd).transpose(0, 3, 1, 2, 4)
    v_r = v_sfx.reshape(layers, b * n_pg, page_size, hkv, hd).transpose(0, 3, 1, 2, 4)
    flat_pages = page_ids.reshape(-1)
    k_pools = tuple(_scatter_pages_kv(pools[0][l], flat_pages, k_r[l])
                    for l in range(layers))
    v_pools = tuple(_scatter_pages_kv(pools[1][l], flat_pages, v_r[l])
                    for l in range(layers))
    return (k_pools, v_pools), last_logits


def make_cache(cfg: ModelConfig, batch: int, max_len: int, dtype=None) -> tuple:
    """Allocate a zeroed KV cache: (k, v) each [L, B, S, Hkv, D]."""
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim_)
    return (jnp.zeros(shape, dtype=dtype), jnp.zeros(shape, dtype=dtype))


def cache_specs(cfg: ModelConfig) -> P:
    """KV cache sharding: batch over (dp, fsdp), heads over tp."""
    return P(None, (DP, FSDP), None, TP, None)
