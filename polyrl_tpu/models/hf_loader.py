"""Load HuggingFace checkpoints (safetensors) into the decoder's pytree.

The reference never loads weights itself — verl/SGLang consume HF
checkpoints directly (reference recipe `run_async_grpo_pipeline.sh:17`
points at Qwen/Qwen3-1.7B). A standalone framework needs its own loader:
this maps the HF llama/qwen parameter naming onto ``decoder.init_params``'s
STACKED-layer pytree, so `get_config(preset) + load_hf_params(ckpt_dir)`
drops pretrained weights straight into training and serving.

Mapping (HF name → pytree path):
- model.embed_tokens.weight            → embed
- model.norm.weight                    → final_norm
- lm_head.weight                       → lm_head (transposed [D, V]; absent
                                         when tie_word_embeddings)
- model.layers.{i}.input_layernorm     → layers.attn_norm[i]
- model.layers.{i}.post_attention_layernorm → layers.mlp_norm[i]
- model.layers.{i}.self_attn.{q,k,v,o}_proj → layers.w{q,k,v,o}[i]
  (transposed: HF Linear stores [out, in], the decoder matmuls x @ W)
- model.layers.{i}.mlp.{gate,up,down}_proj  → layers.w_{gate,up,down}[i]
- model.layers.{i}.self_attn.{q,k}_norm     → layers.{q,k}_norm[i] (Qwen3)
- model.layers.{i}.mlp.gate.weight          → layers.router[i] (Qwen3-MoE)
- model.layers.{i}.mlp.experts.{j}.{gate,up,down}_proj
                                            → layers.we_{gate,up,down}[i, j]

Per-layer tensors are stacked along a leading L axis to match the scan
layout. Loading streams one safetensors shard at a time (file mmap via
``safetensors.safe_open``), so peak host memory ≈ params + one shard.
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np

from polyrl_tpu.models import decoder

_LAYER_MAP = {
    "input_layernorm.weight": "attn_norm",
    "post_attention_layernorm.weight": "mlp_norm",
    "self_attn.q_proj.weight": "wq",
    "self_attn.k_proj.weight": "wk",
    "self_attn.v_proj.weight": "wv",
    "self_attn.o_proj.weight": "wo",
    "mlp.gate_proj.weight": "w_gate",
    "mlp.up_proj.weight": "w_up",
    "mlp.down_proj.weight": "w_down",
    "self_attn.q_norm.weight": "q_norm",
    "self_attn.k_norm.weight": "k_norm",
    "self_attn.q_proj.bias": "bq",  # Qwen2/2.5 attention bias
    "self_attn.k_proj.bias": "bk",
    "self_attn.v_proj.bias": "bv",
}
_TRANSPOSED = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}
# MoE expert tensors: Qwen3-MoE model.layers.{i}.mlp.experts.{j}.<proj>;
# Mixtral model.layers.{i}.block_sparse_moe.experts.{j}.{w1,w3,w2}
_EXPERT_MAP = {
    "gate_proj.weight": "we_gate",
    "up_proj.weight": "we_up",
    "down_proj.weight": "we_down",
    "w1.weight": "we_gate",
    "w3.weight": "we_up",
    "w2.weight": "we_down",
}


def _shard_files(ckpt_dir: str) -> list[str]:
    index = os.path.join(ckpt_dir, "model.safetensors.index.json")
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        return sorted({os.path.join(ckpt_dir, v) for v in weight_map.values()})
    single = os.path.join(ckpt_dir, "model.safetensors")
    if os.path.exists(single):
        return [single]
    raise FileNotFoundError(f"no safetensors checkpoint under {ckpt_dir}")


def config_from_hf(ckpt_dir: str, dtype=jnp.bfloat16) -> decoder.ModelConfig:
    """Build a ModelConfig from the checkpoint's config.json (llama/qwen2/
    qwen3 architectures)."""
    with open(os.path.join(ckpt_dir, "config.json")) as f:
        hf = json.load(f)
    rope_scaling = None
    rs = hf.get("rope_scaling") or {}
    rs_type = rs.get("rope_type", rs.get("type"))
    if rs_type == "llama3":
        rope_scaling = decoder.RopeScaling(
            factor=rs["factor"], low_freq_factor=rs["low_freq_factor"],
            high_freq_factor=rs["high_freq_factor"],
            original_max_position_embeddings=rs["original_max_position_embeddings"])
    elif rs_type not in (None, "default"):
        # silently running yarn/linear/dynamic checkpoints with UNSCALED
        # frequencies would be quietly wrong at long context
        raise NotImplementedError(
            f"rope_scaling type {rs_type!r} is not supported (llama3 only)")
    moe: dict = {}
    if hf.get("num_experts"):  # Qwen3-MoE family
        if hf.get("mlp_only_layers") or (hf.get("decoder_sparse_step", 1) != 1):
            raise NotImplementedError(
                "mixed dense/MoE layer stacks are not supported (uniform "
                "MoE keeps the scan-over-layers body a single trace)")
        moe = dict(
            num_experts=hf["num_experts"],
            num_experts_per_tok=hf.get("num_experts_per_tok", 8),
            moe_intermediate_size=hf["moe_intermediate_size"],
            norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
        )
    elif hf.get("num_local_experts"):  # Mixtral family
        # Mixtral routes softmax(top_k(logits)) — numerically identical to
        # softmax-all → top-k → renormalize (top-k is monotone under
        # softmax and restricting a softmax IS the renormalization), i.e.
        # norm_topk_prob=True; experts use the dense intermediate size
        moe = dict(
            num_experts=hf["num_local_experts"],
            num_experts_per_tok=hf.get("num_experts_per_tok", 2),
            moe_intermediate_size=hf["intermediate_size"],
            norm_topk_prob=True,
        )
    return decoder.ModelConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        **moe,
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=hf.get("head_dim"),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rope_scaling=rope_scaling,
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
        use_qk_norm="qwen3" in hf.get("model_type", ""),
        attention_bias=bool(hf.get("attention_bias",
                                   hf.get("model_type") == "qwen2")),
        max_position_embeddings=hf.get("max_position_embeddings", 131072),
        dtype=dtype,
    )


def load_hf_params(ckpt_dir: str, cfg: decoder.ModelConfig | None = None,
                   dtype=None, quantize: str = "",
                   to_device: bool = True) -> dict:
    """Load a safetensors checkpoint into the decoder pytree. ``cfg``
    defaults to ``config_from_hf(ckpt_dir)``; ``dtype`` defaults to
    ``cfg.dtype``.

    ``quantize="int8"``: matmul weights are quantized ON HOST (numpy) and
    only the int8 tensors + scales are transferred — the full-precision
    tree never exists on device, so an 8B checkpoint loads onto a 16 GiB
    chip (models/quant.py; 8B_FEASIBILITY.md).

    ``to_device=False`` keeps every leaf host-side (numpy): callers that
    shard over a mesh device_put leaf-by-leaf straight into the sharded
    layout, so the unsharded tree never stages through one chip's HBM."""
    from safetensors import safe_open

    from polyrl_tpu.models.quant import (
        QUANTIZED_LAYER_KEYS, QuantWeight, quantize_tensor,
    )

    if quantize not in ("", "int8"):
        raise ValueError(f"unknown quantize mode {quantize!r}")
    cfg = cfg or config_from_hf(ckpt_dir)
    dtype = dtype or cfg.dtype
    np_dtype = jnp.dtype(dtype)

    def _dev(x, dt=None):
        if to_device:
            return jnp.asarray(x, dt) if dt is not None else jnp.asarray(x)
        x = np.asarray(x)
        if dt is not None:
            x = x.astype(jnp.dtype(dt))  # ml_dtypes covers bf16 numpy
        return np.ascontiguousarray(x)
    L = cfg.num_layers

    E = cfg.num_experts
    flat: dict[str, np.ndarray] = {}
    layer_parts: dict[str, list] = {}
    expert_parts: dict[str, list] = {}  # key → [L][E] grid
    for path in _shard_files(ckpt_dir):
        with safe_open(path, framework="np") as f:
            for name in f.keys():
                t = f.get_tensor(name)
                if name == "model.embed_tokens.weight":
                    flat["embed"] = t
                elif name == "model.norm.weight":
                    flat["final_norm"] = t
                elif name == "lm_head.weight":
                    flat["lm_head"] = t.T  # [V, D] → [D, V]
                elif name.startswith("model.layers."):
                    rest = name.split(".", 2)[2]          # "{i}.suffix"
                    idx_s, suffix = rest.split(".", 1)
                    if suffix in ("mlp.gate.weight",
                                  "block_sparse_moe.gate.weight"):  # router
                        layer_parts.setdefault("router", [None] * L)[
                            int(idx_s)] = t.T             # [E, D] → [D, E]
                    elif (suffix.startswith("mlp.experts.")
                          or suffix.startswith("block_sparse_moe.experts.")):
                        j_s, proj = suffix.split(".", 3)[2:]
                        key = _EXPERT_MAP.get(proj)
                        if key is None:
                            raise KeyError(f"unmapped HF expert tensor {name}")
                        grid = expert_parts.setdefault(
                            key, [[None] * E for _ in range(L)])
                        grid[int(idx_s)][int(j_s)] = t.T  # [out,in] → [in,out]
                    else:
                        key = _LAYER_MAP.get(suffix)
                        if key is None:
                            raise KeyError(f"unmapped HF layer tensor {name}")
                        if key in _TRANSPOSED:
                            t = t.T                        # [out,in] → [in,out]
                        layer_parts.setdefault(key, [None] * L)[int(idx_s)] = t
                else:
                    raise KeyError(f"unmapped HF tensor {name}")

    layers = {}
    for key in list(layer_parts):
        parts = layer_parts.pop(key)  # free numpy refs as we convert
        missing = [i for i, p in enumerate(parts) if p is None]
        if missing:
            raise ValueError(f"layer tensors missing for {key}: {missing}")
        stacked = np.stack(parts)
        if quantize == "int8" and key in QUANTIZED_LAYER_KEYS:
            qw = quantize_tensor(stacked, contract_axis=-2)  # host-side
            layers[key] = QuantWeight(q=_dev(qw.q), scale=_dev(qw.scale))
        else:
            layers[key] = _dev(stacked, np_dtype)
    for key in list(expert_parts):
        grid = expert_parts.pop(key)  # [L][E] → [L, E, in, out]
        missing = [(i, j) for i in range(L) for j in range(E)
                   if grid[i][j] is None]
        if missing:
            raise ValueError(f"expert tensors missing for {key}: "
                             f"{missing[:8]}")
        if quantize == "int8":  # experts are the bulk of MoE params
            # quantize PER LAYER before stacking: the f32 transient inside
            # quantize_tensor stays one layer's experts, not the whole
            # [L, E, in, out] stack (which would be ~2× checkpoint size on
            # exactly the large MoE models int8 targets)
            qs, ss = [], []
            for row in grid:
                qw = quantize_tensor(np.stack(row), contract_axis=-2)
                qs.append(qw.q)
                ss.append(qw.scale)
            layers[key] = QuantWeight(q=_dev(np.stack(qs)),
                                      scale=_dev(np.stack(ss)))
        else:
            layers[key] = _dev(
                np.stack([np.stack(row) for row in grid]), np_dtype)

    params = {
        "embed": _dev(flat["embed"], np_dtype),
        "final_norm": _dev(flat["final_norm"], np_dtype),
        "layers": layers,
    }
    if not cfg.tie_word_embeddings:
        if "lm_head" not in flat:
            raise ValueError("checkpoint has no lm_head but config does not "
                             "tie word embeddings")
        if quantize == "int8":
            qw = quantize_tensor(np.ascontiguousarray(flat["lm_head"]),
                                 contract_axis=0)
            params["lm_head"] = QuantWeight(q=_dev(qw.q),
                                            scale=_dev(qw.scale))
        else:
            params["lm_head"] = _dev(flat["lm_head"], np_dtype)
    # structural + shape validation against the config: catches both
    # preset/checkpoint mixups and structurally mismatched checkpoints (a
    # missing q_norm would otherwise surface as an opaque KeyError in jit;
    # an extra bias tensor would be silently ignored at forward time)
    import jax

    if quantize == "int8":
        from polyrl_tpu.models.quant import quantize_params

        shapes = jax.eval_shape(
            lambda: quantize_params(
                decoder.init_params(jax.random.PRNGKey(0), cfg)))
    else:
        shapes = jax.eval_shape(
            lambda: decoder.init_params(jax.random.PRNGKey(0), cfg))
    got = {jax.tree_util.keystr(p): tuple(l.shape)
           for p, l in jax.tree_util.tree_leaves_with_path(params)}
    want = {jax.tree_util.keystr(p): tuple(l.shape)
            for p, l in jax.tree_util.tree_leaves_with_path(shapes)}
    if set(got) != set(want):
        raise ValueError(
            f"checkpoint structure != config: missing {sorted(set(want) - set(got))},"
            f" unexpected {sorted(set(got) - set(want))}")
    for k in got:
        if got[k] != want[k]:
            raise ValueError(
                f"{k}: checkpoint shape {got[k]} != config shape {want[k]}")
    return params


def build_from_hf(ckpt_dir: str, dtype=jnp.bfloat16,
                  overrides: dict | None = None, quantize: str = ""):
    """One-stop: (ModelConfig, params) from a local HF checkpoint dir —
    the shared recipe for the train and serve entry points."""
    import dataclasses

    cfg = config_from_hf(ckpt_dir, dtype=dtype)
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg, load_hf_params(ckpt_dir, cfg, quantize=quantize)
