"""LoRA adapters for RL post-training.

The reference exposes LoRA through verl's actor config but marks it
untested (reference stream_fsdp_workers.py:224 FIXME); here it is a
first-class, tested path. Design: the adapter is a weight WRAPPER
(`quant.LoraWeight`), not a model rewrite — ``decoder`` code is untouched
because ``mm`` dispatches on the wrapper, exactly like int8 QuantWeight.
Wrapping a quantized base gives QLoRA (frozen int8 base + trainable bf16
adapters) with no extra code.

Training: only a/b leaves receive optimizer updates (``lora_mask`` +
``optax.masked``; ``mm`` stops gradients at the base so frozen-weight
grads are structurally zero). Serving: pushes merge the adapters into a
plain tree (``merge_lora``) so the transfer fabric and rollout engines
see the ordinary full-precision layout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from polyrl_tpu.models.quant import LoraWeight, QuantWeight

# default adapter targets: attention + dense MLP projections (MoE expert
# stacks are not wrapped — their einsum path bypasses mm; attention-only
# LoRA is the standard recipe for MoE fine-tuning anyway)
DEFAULT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def wrap_lora(params: dict, rng: jax.Array, rank: int, alpha: float = 16.0,
              targets=DEFAULT_TARGETS, dtype=None) -> dict:
    """Wrap each target layer weight [L, in, out] in a LoraWeight with
    a ~ N(0, 1/r) [L, in, r] and b = 0 [L, r, out] (standard init: the
    adapter starts as an exact no-op)."""
    out = dict(params)
    layers = dict(params["layers"])
    keys = jax.random.split(rng, len(targets))
    for key, k in zip(keys, targets):
        if k not in layers:
            continue
        w = layers[k]
        base_shape = w.shape  # works for plain arrays and QuantWeight
        L, d_in, d_out = base_shape
        dt = dtype or (w.q.dtype if isinstance(w, QuantWeight) else w.dtype)
        if dt == jnp.int8:
            dt = jnp.bfloat16
        a = (jax.random.normal(key, (L, d_in, rank), jnp.float32)
             * (rank ** -0.5)).astype(dt)
        b = jnp.zeros((L, rank, d_out), dt)
        layers[k] = LoraWeight(base=w, a=a, b=b, alpha=float(alpha))
    out["layers"] = layers
    return out


def merge_lora(params: dict) -> dict:
    """Fold adapters into plain full-precision weights: ``base +
    (alpha/r)·a@b``. Quantized bases dequantize to the adapter dtype —
    the push wire and the rollout engines expect the ordinary layout."""

    def merge(w):
        if not isinstance(w, LoraWeight):
            return w
        base = w.base
        if isinstance(base, QuantWeight):
            base = (base.q.astype(jnp.float32)
                    * base.scale[..., None, :]).astype(w.a.dtype)
        rank = w.a.shape[-1]
        delta = jnp.einsum("lir,lro->lio", w.a.astype(jnp.float32),
                           w.b.astype(jnp.float32)) * (w.alpha / rank)
        return (base.astype(jnp.float32) + delta).astype(w.a.dtype)

    out = dict(params)
    out["layers"] = {k: merge(v) for k, v in params["layers"].items()}
    return out


def lora_labels(params: dict) -> dict:
    """'train'/'freeze' label pytree for ``optax.multi_transform``: only
    adapter a/b leaves train; everything else maps to ``set_to_zero`` (NB:
    ``optax.masked`` is NOT suitable — it passes masked-out updates through
    UNCHANGED, i.e. raw gradients would still be applied to the frozen
    embed/norm leaves)."""

    def label(x):
        if isinstance(x, LoraWeight):
            base_lbl = jax.tree_util.tree_map(lambda _: "freeze", x.base)
            return LoraWeight(base=base_lbl, a="train", b="train",
                              alpha=x.alpha)
        return jax.tree_util.tree_map(lambda _: "freeze", x)

    return jax.tree_util.tree_map(
        label, params, is_leaf=lambda x: isinstance(x, LoraWeight))


def lora_optimizer(inner, params: dict):
    """Wrap an optimizer so only adapter leaves update (frozen leaves get
    ``set_to_zero`` — no state, no movement)."""
    import optax

    return optax.multi_transform(
        {"train": inner, "freeze": optax.set_to_zero()},
        param_labels=lora_labels(params))


def lora_param_specs(specs: dict, targets=DEFAULT_TARGETS) -> dict:
    """PartitionSpec tree matching ``wrap_lora`` output: the base keeps its
    spec; a shards like the input dim (fsdp), b like the output dim (tp)."""
    from jax.sharding import PartitionSpec as P

    out = dict(specs)
    layer = dict(specs["layers"])
    for k in targets:
        if k not in layer:
            continue
        s = layer[k]
        if isinstance(s, QuantWeight):  # quantized base spec (QLoRA)
            in_ax, out_ax = s.q[1], s.q[2]
        else:
            in_ax, out_ax = s[1], s[2]
        layer[k] = LoraWeight(base=s, a=P(None, in_ax, None),
                              b=P(None, None, out_ax), alpha=0.0)
    out["layers"] = layer
    return out


def base_stats(params: dict) -> jnp.ndarray:
    """Per-target mean|w| of the first and last layer slabs of each FROZEN
    base, [n_targets, 2] f32 — a cheap provenance fingerprint that rides
    the delta-sync wire. Catches a worker serving a different CHECKPOINT
    than the trainer trains against (layer statistics differ clearly
    across models; int8-vs-bf16 of the SAME checkpoint agrees to <1%).
    It cannot distinguish two random inits of the same architecture —
    delta sync presumes both sides loaded the same pretrained weights."""

    def slab(w):
        if isinstance(w, QuantWeight):
            w = w.q.astype(jnp.float32) * w.scale[..., None, :]
        return jnp.stack([jnp.mean(jnp.abs(w[0])).astype(jnp.float32),
                          jnp.mean(jnp.abs(w[-1])).astype(jnp.float32)])

    rows = [slab(v.base) for k, v in sorted(params["layers"].items())
            if isinstance(v, LoraWeight)]
    return jnp.stack(rows)


def extract_adapters(params: dict) -> dict:
    """The adapter subtree alone: {"layers": {k: {"a": ..., "b": ...}},
    "alpha": scalar, "base_stats": [n_targets, 2]} — what a delta weight
    push puts on the wire (~rank/hidden of the full tree, e.g. ~0.5% at
    rank 16 on an 8B model). ``alpha`` and the base fingerprint ride the
    wire so trainer/worker mismatches fail loudly at apply time instead of
    silently serving a different policy."""
    out: dict = {}
    alpha = None
    for k, v in params["layers"].items():
        if isinstance(v, LoraWeight):
            out[k] = {"a": v.a, "b": v.b}
            alpha = v.alpha
    return {"layers": out, "alpha": jnp.float32(alpha or 0.0),
            "base_stats": base_stats(params)}


def adapter_template(model_cfg, rank: int, targets=DEFAULT_TARGETS,
                     dtype=None) -> dict:
    """ShapeDtypeStruct tree matching ``extract_adapters`` of a wrapped
    model — built from the config alone, so the transfer layout can be
    agreed on by trainer and rollout workers before either holds real
    adapters."""
    from polyrl_tpu.models import decoder

    dt = dtype or model_cfg.dtype
    shapes = jax.eval_shape(
        lambda: decoder.init_params(jax.random.PRNGKey(0), model_cfg))
    out: dict = {}
    for k in targets:
        if k not in shapes["layers"]:
            continue
        L, d_in, d_out = shapes["layers"][k].shape
        out[k] = {
            "a": jax.ShapeDtypeStruct((L, d_in, rank), dt),
            "b": jax.ShapeDtypeStruct((L, rank, d_out), dt),
        }
    return {"layers": out, "alpha": jax.ShapeDtypeStruct((), jnp.float32),
            "base_stats": jax.ShapeDtypeStruct((len(out), 2), jnp.float32)}


def apply_adapters(wrapped: dict, adapters: dict) -> dict:
    """New wrapped tree with the received a/b installed (device_put
    preserving each old leaf's sharding); bases untouched — the rollout
    worker's per-push work is O(adapter bytes), not O(model bytes)."""

    def put(old_leaf, new_host):
        arr = jnp.asarray(np.asarray(new_host), old_leaf.dtype)
        sharding = getattr(old_leaf, "sharding", None)
        return (jax.device_put(arr, sharding) if sharding is not None
                else arr)

    out = dict(wrapped)
    layers = dict(wrapped["layers"])
    if "base_stats" in adapters:
        mine = np.asarray(base_stats(wrapped), np.float32)
        theirs = np.asarray(adapters["base_stats"], np.float32)
        if mine.shape != theirs.shape:
            # different target sets wrapped on each side: the subtraction
            # below would raise a raw broadcast error, not this diagnosis
            raise ValueError(
                "delta-sync base mismatch: trainer and worker wrapped "
                f"different LoRA target sets (fingerprint shapes "
                f"{mine.shape} vs {theirs.shape}); both sides must use the "
                "same checkpoint and target_modules")
        rel = np.abs(mine - theirs) / (np.abs(theirs) + 1e-12)
        if float(rel.max()) > 0.05:
            # the worker's frozen base is not the trainer's checkpoint:
            # installing adapters would silently serve a different policy
            raise ValueError(
                "delta-sync base mismatch: this worker's frozen base "
                f"weights differ from the trainer's (rel diff up to "
                f"{float(rel.max()):.3f}); both sides must load the same "
                "checkpoint")
    recv_alpha = float(np.asarray(adapters.get("alpha", 0.0)))
    for k, ab in adapters["layers"].items():
        w = layers[k]
        if not isinstance(w, LoraWeight):
            raise ValueError(f"adapter push for unwrapped weight {k!r}")
        if recv_alpha and abs(recv_alpha - w.alpha) > 1e-6:
            # alpha scales every delta: a mismatch would silently serve a
            # DIFFERENT policy than the one being trained
            raise ValueError(
                f"lora_alpha mismatch: trainer pushed {recv_alpha}, this "
                f"worker serves {w.alpha} — launch with --lora-alpha "
                f"{recv_alpha}")
        layers[k] = LoraWeight(base=w.base, a=put(w.a, ab["a"]),
                               b=put(w.b, ab["b"]), alpha=w.alpha)
    out["layers"] = layers
    return out


def num_trainable(params: dict) -> int:
    """Adapter parameter count (what the optimizer actually updates)."""
    n = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, LoraWeight)):
        if isinstance(leaf, LoraWeight):
            n += leaf.a.size + leaf.b.size
    return n
