"""Multi-host SPMD helpers for the stream trainer.

TPU-native replacement for the reference's Ray single-controller worker
groups (``stream_fsdp_workers.py:262-546``): instead of a driver scattering
work to N ranks, every host runs the SAME ``fit`` loop (SPMD), the jitted
compute shards over one global mesh (GSPMD inserts the collectives), and the
CONTROL plane — rollout-manager IO, reward scoring, the weight-transfer
fabric, logging — runs on process 0 only, with the assembled batches
broadcast to the other hosts over the jax.distributed client.

The broadcast rides ``multihost_utils.broadcast_one_to_all`` (device
collectives under the hood, so it works over ICI/DCN without a side
channel). Payloads are pickled — batches are host-side numpy at this point
in the pipeline, and control-plane payloads are small next to a generation
phase.
"""

from __future__ import annotations

import pickle
from typing import Any

import jax
import numpy as np


def process_count() -> int:
    return jax.process_count()


def is_main() -> bool:
    return jax.process_index() == 0


def broadcast_obj(obj: Any = None) -> Any:
    """Broadcast an arbitrary picklable object from process 0 to all
    processes. Non-0 processes pass anything (ignored). Two rounds: size,
    then the padded payload (broadcast_one_to_all needs matching shapes)."""
    from jax.experimental import multihost_utils as mhu

    if process_count() == 1:
        return obj
    if is_main():
        payload = np.frombuffer(pickle.dumps(obj), np.uint8)
        size = np.int64(payload.size)
    else:
        payload = np.zeros(0, np.uint8)
        size = np.int64(0)
    size = int(mhu.broadcast_one_to_all(size))
    buf = np.zeros(size, np.uint8)
    if is_main():
        buf[: payload.size] = payload
    buf = np.asarray(mhu.broadcast_one_to_all(buf))
    return pickle.loads(buf.tobytes())


class NullRollout:
    """Rollout placeholder for non-main processes in multi-host runs: the
    control plane (manager streaming, weight push, balancer metrics) lives
    on process 0; other hosts receive their batches via ``broadcast_obj``
    and must never open their own manager/fabric connections."""

    def __init__(self, pad_token_id: int = 0):
        self.pad_token_id = pad_token_id
        self.last_gen_throughput = 0.0
        self.dropped_groups = 0

    def update_weights(self, params: Any, version: int | None = None) -> int:
        return 0

    def update_metrics(self, **stats) -> dict:
        return {}
