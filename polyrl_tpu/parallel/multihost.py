"""Multi-host SPMD helpers for the stream trainer.

TPU-native replacement for the reference's Ray single-controller worker
groups (``stream_fsdp_workers.py:262-546``): instead of a driver scattering
work to N ranks, every host runs the SAME ``fit`` loop (SPMD), the jitted
compute shards over one global mesh (GSPMD inserts the collectives), and the
CONTROL plane — rollout-manager IO, reward scoring, the weight-transfer
fabric, logging — runs on process 0 only, with the assembled batches
broadcast to the other hosts over the jax.distributed client.

The broadcast rides ``multihost_utils.broadcast_one_to_all`` (device
collectives under the hood, so it works over ICI/DCN without a side
channel). Payloads are pickled — batches are host-side numpy at this point
in the pipeline, and control-plane payloads are small next to a generation
phase.
"""

from __future__ import annotations

import pickle
from typing import Any

import jax
import numpy as np


def process_count() -> int:
    return jax.process_count()


def is_main() -> bool:
    return jax.process_index() == 0


def broadcast_obj(obj: Any = None) -> Any:
    """Broadcast an arbitrary picklable object from process 0 to all
    processes. Non-0 processes pass anything (ignored). Two rounds: size,
    then the padded payload (broadcast_one_to_all needs matching shapes)."""
    from jax.experimental import multihost_utils as mhu

    if process_count() == 1:
        return obj
    if is_main():
        payload = np.frombuffer(pickle.dumps(obj), np.uint8)
        size = np.int64(payload.size)
    else:
        payload = np.zeros(0, np.uint8)
        size = np.int64(0)
    size = int(mhu.broadcast_one_to_all(size))
    buf = np.zeros(size, np.uint8)
    if is_main():
        buf[: payload.size] = payload
    buf = np.asarray(mhu.broadcast_one_to_all(buf))
    return pickle.loads(buf.tobytes())


def broadcast_batch(tagged: tuple[str, Any] | None = None) -> tuple[str, Any]:
    """Hot-path broadcast for the per-ibatch data plane: a ``("batch",
    TensorBatch)`` message ships as a small pickled HEADER (tag, tensor
    specs, non-tensors/meta) plus ONE raw-bytes round carrying the tensor
    payload — the arrays never pass through pickle, and receivers rebuild
    them as zero-copy views into the broadcast buffer. Any other tag
    (``("end", ...)`` / ``("error", ...)``) rides the header alone.

    At pod scale this is what keeps the control-plane fan-out off the step
    critical path: pickling a batch copies every array and the generic
    object broadcast re-copies the pickle; here the payload is one
    contiguous buffer handed straight to the collective. Measured 1.7x
    over ``broadcast_obj`` on a 14.7 MB ibatch across 2 loopback-gloo
    processes (tools/bench_broadcast.py) — the gap widens with real DCN
    latency and payload size.
    """
    from jax.experimental import multihost_utils as mhu

    from polyrl_tpu.data.batch import TensorBatch

    if process_count() == 1:
        return tagged
    specs = None
    total = 0
    arrays: list[np.ndarray] = []
    if is_main():
        kind, payload = tagged
        if kind == "batch" and isinstance(payload, TensorBatch):
            specs = []
            for k, v in payload.tensors.items():
                arr = np.ascontiguousarray(np.asarray(v))
                # dtype object (not .str): pickled in the header, so exotic
                # dtypes (bfloat16 via ml_dtypes) round-trip too
                specs.append((k, arr.dtype, arr.shape, arr.nbytes))
                arrays.append(arr)
            total = sum(s[3] for s in specs)
            header = (kind, None,
                      (specs, total, payload.non_tensors, payload.meta_info))
        else:
            header = (kind, payload, None)
        broadcast_obj(header)
    else:
        kind, payload, extra = broadcast_obj(None)
        if extra is None:
            return kind, payload
        specs, total, non_tensors, meta_info = extra
    if specs is None:  # main, non-batch tag: header already carried it
        return tagged
    # np.empty, not zeros: main overwrites every byte below and receivers'
    # contents are replaced by the collective — a memset of the whole batch
    # per ibatch is pure waste on the hot path
    buf = np.empty(max(total, 1), np.uint8)
    if is_main():
        off = 0
        for arr in arrays:
            n = arr.nbytes
            buf[off : off + n] = arr.view(np.uint8).reshape(-1)
            off += n
    raw = np.asarray(mhu.broadcast_one_to_all(buf))
    if is_main():
        return tagged
    tensors = {}
    off = 0
    for k, dtype, shape, nbytes in specs:
        tensors[k] = raw[off : off + nbytes].view(dtype).reshape(shape)
        off += nbytes
    return "batch", TensorBatch(tensors=tensors, non_tensors=non_tensors,
                                meta_info=meta_info)


class NullRollout:
    """Rollout placeholder for non-main processes in multi-host runs: the
    control plane (manager streaming, weight push, balancer metrics) lives
    on process 0; other hosts receive their batches via ``broadcast_batch``
    (header + raw-bytes fast path) and must never open their own
    manager/fabric connections."""

    def __init__(self, pad_token_id: int = 0):
        self.pad_token_id = pad_token_id
        self.last_gen_throughput = 0.0
        self.dropped_groups = 0

    def update_weights(self, params: Any, version: int | None = None) -> int:
        return 0

    def update_metrics(self, **stats) -> dict:
        return {}
