"""Version-portable ``shard_map`` for the jax span this repo supports.

``jax.shard_map`` (top-level, keyword ``check_vma``/``axis_names``) only
exists on newer jax; this image's 0.4.37 ships the experimental API
(``jax.experimental.shard_map.shard_map``) whose equivalent keywords are
``check_rep`` and ``auto``. The two differ in more than spelling:

- ``check_vma=False``  ==  ``check_rep=False`` (skip the replication /
  varying-manual-axes check; our kernels wrap custom calls the checker
  can't see through).
- ``axis_names={...}`` names the axes the body IS manual over, while the
  old ``auto={...}`` names the mesh axes the body is NOT manual over —
  so ``auto = mesh.axis_names - axis_names``.

Every in-repo shard_map goes through this shim; call sites use the NEW
spelling and the shim down-translates when running on the legacy API.
"""

from __future__ import annotations

import jax

_NEW = hasattr(jax, "shard_map")
if not _NEW:  # legacy experimental API (jax <= 0.4.x)
    from jax.experimental.shard_map import shard_map as _legacy_shard_map


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
              axis_names=None):
    """``jax.shard_map`` with new-style kwargs on any supported jax.

    ``axis_names``: the mesh axes the body is manual over (None = all).
    ``check_vma``: the new-API replication/VMA check toggle (None = API
    default).
    """
    if _NEW:
        kwargs = {}
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    kwargs = {}
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    # ``axis_names`` maps to legacy ``auto = mesh_axes - axis_names`` — but
    # legacy partial-auto lowering is broken on this jaxlib (axis_index
    # emits a PartitionId op the SPMD partitioner rejects; threading the
    # index as an input instead trips a manual-subgroup CHECK crash). All
    # in-repo partial-manual regions take inputs replicated over their auto
    # axes (pipeline.py param/activation specs name only pp/sp), so the
    # correct legacy fallback is FULLY manual: the body replicates over the
    # would-be-auto axes — identical math, only losing intra-region
    # GSPMD sharding over those axes on old jax.
    return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
