"""Parallelism: device mesh axes + sequence/context parallel attention."""

from .mesh import AXES, BATCH_SPEC, DP, FSDP, SP, TP, MeshConfig, make_mesh
from .sequence import (
    make_ring_attention,
    make_sp_attention,
    make_ulysses_attention,
)

__all__ = [
    "AXES", "BATCH_SPEC", "DP", "FSDP", "SP", "TP", "MeshConfig", "make_mesh",
    "make_ring_attention", "make_sp_attention", "make_ulysses_attention",
]
