"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` axis.

The reference only stubs pipeline parallelism (``infer_pp`` config knob,
reference workers/config/rollout.py:132-134,198-202 — guarded
unimplemented); here it is a real execution mode, built the TPU-idiomatic
way: ONE compiled program, not per-stage processes.

- The stacked layer tree [L, ...] reshapes to [pp, L/pp, ...] and shards
  its leading (stage) dim over the ``pp`` mesh axis.
- A ``shard_map`` manual only on ``pp`` (jax partial-manual mode) runs the
  rotating schedule: at global step s, stage i applies its L/pp layers to
  microbatch (s - i), then hands its activation to stage i+1 via
  ``lax.ppermute``. Inside the stage body the other mesh axes (fsdp/tp/
  ep/...) stay AUTO, so GSPMD keeps inserting the usual FSDP all-gathers
  and TP collectives — pipeline composes with the existing shardings
  instead of re-implementing them.
- Backward needs no separate schedule: autodiff transposes ``ppermute``
  into the reverse rotation, which IS the backward pipeline.

Bubble fraction is the GPipe (pp-1)/(n_micro+pp-1); raise
``num_microbatches`` to amortize. Activations for all microbatches are
held replicated across stages (simple and correct; revisit if activation
memory ever dominates at depth).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from polyrl_tpu.parallel.compat import shard_map
from polyrl_tpu.parallel.mesh import PP, SP


def make_pipeline_layers_fn(mesh: Mesh, cfg, num_microbatches: int,
                            remat: bool = False, sp_ring: bool = False):
    """Returns ``layers_fn(layers, x, cos, sin, attn_mask)`` — a drop-in
    for the decoder's layer-stack scan (decoder.forward ``layers_fn``
    hook): x [B, T, d] → [B, T, d] with the stack executed as a pipeline.

    Requires ``cfg.num_layers % pp == 0`` and ``B % num_microbatches == 0``.

    ``sp_ring=True`` composes SEQUENCE parallelism into the pipeline: the
    shard_map goes manual on {pp, sp}, activations keep their seq dim
    sharded over sp, and the stage attention runs
    :func:`polyrl_tpu.parallel.sequence.ring_attention_local` — K/V blocks
    ring over sp INSIDE each stage while microbatches ring over pp. Needs
    ``T % sp == 0``. (Ulysses inside the stages is not implemented: its
    head all-to-all would reshard every stage boundary.)
    """
    from polyrl_tpu.models import decoder as _dec

    pp = mesh.shape[PP]
    sp = mesh.shape[SP] if sp_ring else 1
    n = num_microbatches
    if cfg.num_layers % pp != 0:
        raise ValueError(f"num_layers {cfg.num_layers} not divisible by "
                         f"pp {pp}")
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def stage_apply(stage_layers, h, cos, sin, valid, seg):
        # stage attention goes through the flash wrapper (Pallas O(T)
        # memory on TPU, dense fallback elsewhere — ops/flash.py), NOT a
        # materialized [T, T] mask: packed long-context is exactly the
        # workload where dense per-stage logits would O(T²) the pipeline.
        # ``seg`` carries real segment ids in the packed case and the
        # validity mask (pad=0) otherwise — identical semantics to the
        # mask-derived ids flash uses everywhere else.
        # CAVEAT (hardware validation pending): the Pallas flash kernel
        # inside this partial-manual shard_map has only executed via the
        # CPU dense fallback on this rig — supports_flash() gates it off
        # for untileable shapes, but a TPU lowering failure of the
        # supported path would only surface on real hardware (same
        # exposure as every training-path flash call since r1).
        from polyrl_tpu.ops import flash

        am = valid.astype(h.dtype)
        if sp_ring:
            # seq dim is LOCAL (T/sp); ring the K/V blocks over sp within
            # this stage — global causality comes from the ring's own
            # axis-index positioning
            from polyrl_tpu.parallel.sequence import ring_attention_local

            attn = lambda q, k, v: ring_attention_local(  # noqa: E731
                q, k, v, am, seg, axis=SP, sp=sp)
        else:
            attn = lambda q, k, v: flash.flash_attention_train(  # noqa: E731
                q, k, v, am, causal=True, segment_ids=seg)

        def body(carry, lp):
            out, _ = _dec._layer_forward(cfg, carry, lp, cos, sin, None,
                                         None, attn_fn=attn,
                                         token_valid=valid)
            return out, None
        if remat:
            body = jax.checkpoint(body)
        h, _ = lax.scan(body, h, stage_layers)
        return h

    def inner(stage_layers, xs, coss, sins, valids, segs):
        # manual on pp only: stage dim is local (length 1) — drop it
        stage_layers = jax.tree_util.tree_map(lambda a: a[0], stage_layers)
        stage = lax.axis_index(PP)
        state = jnp.zeros(xs.shape[1:], xs.dtype)
        outs = jnp.zeros_like(xs)

        def step_fn(carry, step):
            state, outs = carry
            # stage i works on microbatch (step - i); clip keeps indices
            # static-shaped — the warm-up/drain garbage never reaches a
            # real output slot (see write guard below)
            mb = jnp.clip(step - stage, 0, n - 1)
            inp = jnp.where(stage == 0, xs[jnp.clip(step, 0, n - 1)], state)
            h = stage_apply(stage_layers, inp, coss[mb], sins[mb],
                            valids[mb], segs[mb])
            out_idx = step - (pp - 1)
            ok = (stage == pp - 1) & (out_idx >= 0)
            oi = jnp.clip(out_idx, 0, n - 1)
            upd = jnp.where(ok, h, lax.dynamic_index_in_dim(
                outs, oi, 0, keepdims=False))
            outs = lax.dynamic_update_index_in_dim(outs, upd, oi, 0)
            state = lax.ppermute(h, PP, perm)
            return (state, outs), None

        (_, outs), _ = lax.scan(step_fn, (state, outs),
                                jnp.arange(n + pp - 1))
        # only the last stage wrote real outputs; everyone else holds
        # zeros — the psum replicates the result across the ring
        return lax.psum(outs, PP)

    def layers_fn(layers, x, cos, sin, attn_mask, segment_ids=None):
        """``segment_ids`` (optional [B, T], 0 = pad): packed
        (remove-padding) rows — the stages' internal attention masks turn
        block-diagonal within segments, composing packed training with
        pipeline parallelism (the packed caller binds them per batch via a
        closure, exactly like its attn lambda)."""
        b, t, d = x.shape
        # total over ANY batch size: logprob feeds (ibatch-sized) and
        # ragged tail micros flow through the same layers_fn as the
        # configured micro batches — pad rows up to a microbatch multiple
        # (fully masked: attention sees nothing, MoE routing skips them)
        # and slice back after
        b_pad = -(-b // n) * n
        if b_pad != b:
            grow = b_pad - b
            x = jnp.pad(x, ((0, grow), (0, 0), (0, 0)))
            cos = jnp.pad(cos, ((0, grow),) + ((0, 0),) * (cos.ndim - 1))
            sin = jnp.pad(sin, ((0, grow),) + ((0, 0),) * (sin.ndim - 1))
            attn_mask = jnp.pad(attn_mask, ((0, grow), (0, 0)))
            if segment_ids is not None:
                segment_ids = jnp.pad(segment_ids, ((0, grow), (0, 0)))
        mb = b_pad // n
        lpp = cfg.num_layers // pp
        staged = jax.tree_util.tree_map(
            lambda a: a.reshape((pp, lpp) + a.shape[1:]), layers)
        xs = x.reshape(n, mb, t, d)
        coss = cos.reshape((n, mb) + cos.shape[1:])
        sins = sin.reshape((n, mb) + sin.shape[1:])
        valids = (attn_mask > 0).reshape(n, mb, t)
        segs = (segment_ids if segment_ids is not None
                else (attn_mask > 0).astype(jnp.int32)).reshape(n, mb, t)

        specs = jax.tree_util.tree_map(lambda _: P(PP), staged)
        if sp_ring:
            if t % sp != 0:
                raise ValueError(
                    f"sp_ring pipeline needs seq len {t} divisible by "
                    f"sp {sp}")
            # seq dim (index 2 after the [n, mb, ...] reshape) shards over
            # sp; params stay replicated over sp (their specs name only pp)

            def seq_spec(a):
                return P(*([None, None, SP] + [None] * (a.ndim - 3)))

            in_specs = (specs, seq_spec(xs), seq_spec(coss), seq_spec(sins),
                        P(None, None, SP), P(None, None, SP))
            out_spec = P(None, None, SP, None)
            manual = {PP, SP}
        else:
            in_specs = (specs, P(), P(), P(), P(), P())
            out_spec = P()
            manual = {PP}
        fn = shard_map(
            inner, mesh=mesh, in_specs=in_specs,
            out_specs=out_spec, axis_names=manual, check_vma=False)
        outs = fn(staged, xs, coss, sins, valids, segs)
        return outs.reshape(b_pad, t, d)[:b]

    return layers_fn
