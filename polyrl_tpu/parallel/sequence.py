"""Sequence/context parallelism: Ulysses all-to-all + ring attention.

The reference's long-context training mechanism is verl's Ulysses SP —
sequences sliced along length across ranks, attention computed by
all-to-all head exchange (SURVEY §5.7, ``stream_fsdp_workers.py:91``,
``stream_dp_actor.py:37``). The reference has no ring attention; SURVEY §2.3
calls for providing ring attention over ICI as the TPU-idiomatic context
parallelism for the very-long-context regime.

Both primitives run under ``shard_map`` over the ``sp`` mesh axis and share
one signature: q/k/v are [B, T, H, D] logically-global arrays sharded
P(batch, sp, None, None); ``token_mask`` is [B, T] validity (left-pad
aware); causal masking over GLOBAL positions is applied internally.

``packed=True`` returns the segment-aware variant — signature gains a
``segment_ids`` [B, T] argument (0 = pad, 1-based per row) and attention is
block-diagonal within segments, composing remove-padding training with SP
exactly as the reference's Ulysses slices packed varlen inputs
(``stream_dp_actor.py:37-47,135`` — its default long-context mode). A
packed segment may SPAN the rank boundary: the all-to-all / ring exchange
re-unifies the sequence before masking, so equality against gathered (or
rotating) segment ids is exact regardless of where the slice fell.

SP composes with TENSOR parallelism: the head dim of q/k/v is sharded over
``tp`` in the shard_map specs, so tp-sharded projections feed straight in
with no head all-gather. Ring attention never moves heads, so tp>1 is free;
Ulysses all-to-alls each tp shard's LOCAL heads over sp (correct because
heads shard contiguously over tp first: local q head j maps to local KV
head j // (Hq/Hkv) exactly as in the global layout, given Hkv % tp == 0 —
the constraint tp decoding already imposes). Ulysses therefore needs
``num_heads % (tp * sp) == 0``; train.py validates.

- Ulysses: all-to-all redistributes heads<->sequence so each rank computes
  full-sequence attention for H/sp heads — one cheap ICI all-to-all each
  way, best when H >= sp.
- Ring: K/V blocks rotate around the sp ring via ``ppermute`` with online
  (flash-style) softmax accumulation — memory O(T/sp) per rank, scales to
  sequences no single chip can hold.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from polyrl_tpu.ops.attention import repeat_kv
from polyrl_tpu.parallel.compat import shard_map
from polyrl_tpu.parallel.mesh import DP, FSDP, SP, TP

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)  # finite -inf (no exp NaNs)


def _expand_kv_minimal(k, v, hq: int, sp: int):
    """GQA under Ulysses: KV heads ride the same all-to-all as Q heads, so
    their count must divide by sp. When ``hkv % sp != 0``, expand by the
    SMALLEST factor r (r must divide the GQA group hq/hkv so head↔group
    association survives the head split, and make hkv*r % sp == 0) —
    full expansion to hq only as the last resort. This keeps most of the
    GQA memory win, e.g. hkv=8, hq=32, sp=16 expands 2× not 4×."""
    hkv = k.shape[2]
    if hkv % sp == 0:
        return k, v
    group = hq // hkv
    r = next((r for r in range(2, group + 1)
              if group % r == 0 and (hkv * r) % sp == 0), group)
    return repeat_kv(k, r), repeat_kv(v, r)


# --------------------------------------------------------------------------
# Ulysses
# --------------------------------------------------------------------------


def make_ulysses_attention(mesh: Mesh, axis: str = SP,
                           batch_axes=(DP, FSDP), packed: bool = False):
    """Returns attn_fn(q, k, v, token_mask) -> out, all [B, T, H, D] with the
    seq dim sharded over ``axis``. Ulysses ≙ all-to-all head redistribution
    (verl's FSDPUlyssesShardingManager equivalent). ``packed=True``: the fn
    takes a trailing ``segment_ids`` and the gathered full-sequence
    attention runs the SAME segment-id flash kernel as the non-SP packed
    path (Pallas on TPU, dense fallback elsewhere — ops/flash.py)."""
    sp = mesh.shape[axis]

    def _exchange(q, k, v):
        # local: q [B, Ts, Hq, D]; all_to_all -> [B, T, Hq/sp, D]
        k, v = _expand_kv_minimal(k, v, q.shape[2], sp)
        q_g = lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
        k_g = lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
        v_g = lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
        return q_g, k_g, v_g

    def inner(q, k, v, token_mask, segment_ids=None):
        # the gathered full-sequence attention runs the flash kernel
        # (Pallas on TPU — O(T) memory; the whole point of SP is sequence
        # lengths where dense [B, H, T, T] logits cannot exist), with the
        # dense masked fallback off-TPU / non-tiling shapes (ops/flash.py).
        # Without explicit segment ids, padding rides the mask-derived ids
        # (pad=0 attends only pads; pad rows are garbage either way and
        # the loss masks them).
        from polyrl_tpu.ops import flash

        q_g, k_g, v_g = _exchange(q, k, v)
        mask_g = lax.all_gather(token_mask, axis, axis=1, tiled=True)  # [B, T]
        seg_g = (lax.all_gather(segment_ids, axis, axis=1, tiled=True)
                 if segment_ids is not None else None)
        out = flash.flash_attention_train(q_g, k_g, v_g, mask_g, causal=True,
                                          segment_ids=seg_g)
        return lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)

    qkv_spec = P(batch_axes, axis, TP, None)  # heads stay tp-sharded
    mask_spec = P(batch_axes, axis)
    if packed:
        return shard_map(
            inner, mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec, mask_spec),
            out_specs=qkv_spec, check_vma=False)
    return shard_map(
        lambda q, k, v, tm: inner(q, k, v, tm), mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec, check_vma=False)


# --------------------------------------------------------------------------
# Ring attention
# --------------------------------------------------------------------------


def ring_attention_local(q, k, v, token_mask, segment_ids=None, *,
                         axis: str = SP, sp: int):
    """The ring-attention body for use INSIDE a shard_map region that is
    manual on ``axis``: q/k/v are the LOCAL [b, T/sp, H, D] blocks; K/V
    (with their mask/segment ids) rotate around the ring via ``ppermute``
    with online-softmax merging over GLOBAL positions. Exposed so the
    pipeline's stage attention can run it inside its own manual region
    (sp × pp composition); ``make_ring_attention`` is the standalone
    shard_map wrapper.

    GQA-native: heads never leave their rank, so KV is NOT expanded at
    all — the rotating K/V blocks stay at hkv heads (the dominant
    memory/ICI cost) and Q heads group against their shared KV head in
    the einsum, exactly like ops.attention."""
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    b, tq, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    scale = d ** -0.5
    idx = lax.axis_index(axis)
    q32 = q.reshape(b, tq, hkv, g, d).astype(jnp.float32) * scale
    q_pos = idx * tq + jnp.arange(tq)  # global positions of local Q rows

    m = jnp.full((b, hkv, g, tq), _NEG, jnp.float32)
    l = jnp.zeros((b, hkv, g, tq), jnp.float32)
    o = jnp.zeros((b, tq, hkv, g, d), jnp.float32)
    k_cur, v_cur, mask_cur, seg_cur = k, v, token_mask, segment_ids

    for step in range(sp):
        src = (idx - step) % sp  # block id currently held
        tk = k_cur.shape[1]
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", q32,
                            k_cur.astype(jnp.float32))
        kv_pos = src * tk + jnp.arange(tk)
        ok = (kv_pos[None, :] <= q_pos[:, None])[None, None, None, :, :]
        ok = ok & (mask_cur[:, None, None, None, :] > 0)
        if seg_cur is not None:
            ok = ok & (segment_ids[:, :, None]
                       == seg_cur[:, None, :])[:, None, None, :, :]
        logits = jnp.where(ok, logits, _NEG)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        p = jnp.where(ok, p, 0.0)
        corr = jnp.exp(m - m_new)                      # [b,hkv,g,tq]
        l = l * corr + p.sum(axis=-1)
        o = o * corr.transpose(0, 3, 1, 2)[..., None] + jnp.einsum(
            "bhgqk,bkhd->bqhgd", p, v_cur.astype(jnp.float32))
        m = m_new
        if step < sp - 1:
            k_cur = lax.ppermute(k_cur, axis, perm)
            v_cur = lax.ppermute(v_cur, axis, perm)
            mask_cur = lax.ppermute(mask_cur, axis, perm)
            if seg_cur is not None:
                seg_cur = lax.ppermute(seg_cur, axis, perm)

    denom = jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    return (o / denom).reshape(b, tq, hq, d).astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis: str = SP, batch_axes=(DP, FSDP),
                        packed: bool = False):
    """Returns attn_fn(q, k, v, token_mask) -> out over a standalone
    shard_map (manual on ``axis``) around :func:`ring_attention_local` —
    the TPU context-parallel mode SURVEY §2.3 calls for. ``packed=True``:
    segment ids rotate WITH their K/V block and the mask adds same-segment
    equality (block-diagonal packed attention)."""
    sp = mesh.shape[axis]

    def inner(q, k, v, token_mask, segment_ids=None):
        return ring_attention_local(q, k, v, token_mask, segment_ids,
                                    axis=axis, sp=sp)

    qkv_spec = P(batch_axes, axis, TP, None)  # heads stay tp-sharded
    mask_spec = P(batch_axes, axis)
    if packed:
        return shard_map(
            inner, mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec, mask_spec),
            out_specs=qkv_spec, check_vma=False)
    return shard_map(
        lambda q, k, v, tm: inner(q, k, v, tm), mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec, check_vma=False)


def make_sp_attention(mesh: Mesh, mode: str, axis: str = SP,
                      batch_axes=(DP, FSDP), packed: bool = False):
    """Dispatch: 'ulysses' | 'ring' | 'dense' (None)."""
    if mode == "ulysses":
        return make_ulysses_attention(mesh, axis, batch_axes, packed=packed)
    if mode == "ring":
        return make_ring_attention(mesh, axis, batch_axes, packed=packed)
    if mode in ("dense", "none", None):
        return None
    raise ValueError(f"unknown sp attention mode {mode!r}")
