"""Device mesh construction and named sharding axes.

TPU-native equivalent of the reference's parallelism inventory (SURVEY.md
§2.3). The reference composes FSDP sharding + rollout dp×infer_tp×infer_pp
meshes (``stream_fsdp_workers.py:126-135``) + Ulysses SP; here all of it is
one ``jax.sharding.Mesh`` with five logical axes:

- ``dp``    data parallel (batch dim)
- ``fsdp``  ZeRO-style parameter sharding (combines with dp for the batch)
- ``tp``    tensor/model parallel (MXU-dim sharding, rides ICI)
- ``sp``    sequence/context parallel (Ulysses all-to-all or ring attention)
- ``ep``    expert parallel (MoE expert dim; GSPMD inserts the dispatch/
            combine all-to-alls from the einsum shardings)
- ``pp``    pipeline parallel (layer-stack stages; GPipe microbatch
            schedule via shard_map + ppermute, parallel/pipeline.py)

Training batches shard over (dp, fsdp); params shard over (fsdp, tp) with
MoE expert weights additionally over ep and the layer stack over pp;
sequence dim over sp. XLA inserts the collectives (GSPMD), so FSDP
all-gather/reduce-scatter and the TP broadcast of the reference's NCCL
world disappear into the compiled program.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP, FSDP, TP, SP, EP, PP = "dp", "fsdp", "tp", "sp", "ep", "pp"
AXES = (DP, FSDP, TP, SP, EP, PP)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    fsdp: int = -1  # -1: absorb remaining devices
    tp: int = 1
    sp: int = 1
    # Pipeline parallelism: a REAL axis (beyond the reference, which only
    # stubs infer_pp, workers/config/rollout.py:132-134,198-202) — the
    # layer stack reshapes to [pp, L/pp, ...] sharded over it and runs the
    # GPipe microbatch schedule (parallel/pipeline.py: shard_map +
    # ppermute; autodiff through the permutes gives the backward schedule).
    pp: int = 1
    # Expert parallelism: a REAL axis (beyond the reference, which stubs
    # expert knobs at workers/config/rollout.py:193-196) — MoE expert
    # weights shard over it (models/decoder.py MoE param specs) and GSPMD
    # derives the dispatch/combine all-to-alls from the einsum shardings.
    ep: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int, int, int, int]:
        dims = [self.dp, self.fsdp, self.tp, self.sp, self.ep, self.pp]
        fixed = 1
        for d in dims:
            if d != -1:
                fixed *= d
        if n_devices % fixed != 0:
            raise ValueError(f"{n_devices} devices not divisible by fixed axes {fixed}")
        free = n_devices // fixed
        dims = [free if d == -1 else d for d in dims]
        if int(np.prod(dims)) != n_devices:
            raise ValueError(f"mesh {dims} != {n_devices} devices (use one -1 axis)")
        return tuple(dims)


def make_mesh(config: MeshConfig | None = None, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build the 6-axis training/rollout mesh.

    Axis order is (dp, fsdp, tp, sp, ep, pp) — tp/ep (the latency-critical
    axes) sit toward the innermost, fastest ICI rings; pipeline stages
    communicate only once per microbatch step so pp tolerates the
    outermost placement.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    dims = config.resolve(len(devices))
    dev_array = np.array(devices).reshape(dims)
    return Mesh(dev_array, AXES)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    dev = device if device is not None else jax.devices()[0]
    return Mesh(np.array([dev]).reshape(1, 1, 1, 1, 1, 1), AXES)


# -- canonical partition specs --------------------------------------------

# batch-dim sharding for activations/data: batch over (dp, fsdp), seq over sp
BATCH_SPEC = P((DP, FSDP), SP)
# token ids [B, T]
TOKENS_SPEC = P((DP, FSDP), SP)
# logits [B, T, V] — vocab over tp
LOGITS_SPEC = P((DP, FSDP), SP, TP)
REPLICATED = P()


def sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def shard_params(mesh: Mesh, params, specs):
    """device_put a param pytree with per-leaf specs from a matching (or
    partially matching) spec tree: leaves without a spec (e.g. a critic's
    value head absent from ``decoder.param_specs``) fall back to replicated.
    The single shared implementation for actor/critic GSPMD placement.

    Spec lookup is by FLATTENED key path (not dict indexing), so spec trees
    containing pytree nodes without ``__getitem__`` — e.g. quant.QuantWeight
    wrapping (q_spec, scale_spec) — resolve correctly instead of silently
    falling back to replicated."""
    by_path = {
        jax.tree_util.keystr(p): s
        for p, s in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))[0]
    }

    def put(path, x):
        node = by_path.get(jax.tree_util.keystr(path), P())
        if not isinstance(node, P):
            node = P()
        return jax.device_put(x, NamedSharding(mesh, node))

    return jax.tree_util.tree_map_with_path(put, params)


def shard_batch(mesh: Mesh, tree, spec: P = BATCH_SPEC):
    """device_put a pytree of [B, ...] arrays with batch-dim sharding.

    Arrays whose rank is 1 get P((dp, fsdp)); rank ≥2 get ``spec`` truncated
    to their rank.
    """

    def put(x):
        r = np.ndim(x)
        if r == 0:
            return jax.device_put(x, NamedSharding(mesh, P()))
        parts = list(spec)[:r]
        parts += [None] * (r - len(parts))
        return jax.device_put(x, NamedSharding(mesh, P(*parts)))

    return jax.tree_util.tree_map(put, tree)
