"""Multi-host / multi-slice initialization and hybrid DCN×ICI meshes.

TPU-native equivalent of the reference's distributed backends (SURVEY.md
§2.4): torch.distributed/NCCL process groups become ``jax.distributed``
(one process per host, XLA collectives over ICI inside a slice and DCN
across slices). The reference's trainer ranks discover each other through
Ray; here coordinator discovery uses the standard TPU env vars (or explicit
arguments), so the same entry point works under any launcher.

Mesh layout guidance (scaling-book recipe): put the OUTER (slowest) axis on
DCN — cross-slice data parallelism — and keep tp/sp/fsdp inside a slice on
ICI. ``make_hybrid_mesh`` builds exactly that via
``mesh_utils.create_hybrid_device_mesh``.
"""

from __future__ import annotations

import logging
import os

import jax

from polyrl_tpu.parallel import mesh as meshlib

log = logging.getLogger(__name__)


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Bring up jax.distributed for multi-host execution. No-ops when
    single-process (num_processes == 1 or nothing configured). Arguments
    default to the standard env vars (JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID), which TPU pod launchers set."""
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "0") or 0)
    if process_id is None:
        pid = os.environ.get("JAX_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    if not coordinator_address or num_processes <= 1:
        log.info("single-process run; jax.distributed not initialized")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info("jax.distributed up: process %d/%d, %d local / %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())


def make_hybrid_mesh(dcn_dp: int | None = None,
                     config: "meshlib.MeshConfig | None" = None) -> jax.sharding.Mesh:
    """Hybrid DCN×ICI mesh: ``dcn_dp`` slices data-parallel over DCN (one
    entry per slice/granule), everything else (fsdp/tp/sp from ``config``)
    inside the slice on ICI. Falls back to the flat mesh single-slice."""
    from jax.experimental import mesh_utils

    # dcn_dp = number of DISTINCT slices (DCN granules), not process count:
    # a multi-host single-slice pod (e.g. v4-32: 4 processes, 1 slice) must
    # resolve to dcn_dp=1 or create_hybrid_device_mesh rejects the shape
    slice_ids = {getattr(d, "slice_index", None) for d in jax.devices()}
    if dcn_dp is None:
        dcn_dp = len(slice_ids) if None not in slice_ids else 1
    if dcn_dp <= 1:
        return meshlib.make_mesh(config)
    per_slice = jax.device_count() // dcn_dp
    cfg = config or meshlib.MeshConfig()
    dp, fsdp, tp, sp, ep, pp = cfg.resolve(per_slice)
    devices = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(dp, fsdp, tp, sp, ep, pp),
        dcn_mesh_shape=(dcn_dp, 1, 1, 1, 1, 1),
        devices=jax.devices(),
    )
    return jax.sharding.Mesh(devices, meshlib.AXES)
