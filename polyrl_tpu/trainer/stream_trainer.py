"""StreamRLTrainer — the streaming PPO/GRPO fit loop.

TPU-native equivalent of the reference's C2 ``StreamRayPPOTrainer.fit``
(``stream_ray_trainer.py:282-707``): per training batch, rollout responses
arrive as micro-batches ("ibatches") of at least ``min_stream_batch_size``;
each ibatch flows reward → old_logprob → ref_logprob → values → advantage,
then actor/critic fwd/bwd with gradient accumulation; the optimizer steps at
cumulative minibatch boundaries (reference :500-568); weights push to the
rollout engine after each step (:571-575); metrics feed the balancer
(:691-704).

Two rollout modes behind one loop:
- **colocated** (reference ``main_ppo`` baseline, SURVEY.md §3.5): an
  in-process engine generates the full batch, then ibatches are slices.
- **disaggregated streaming** (the reference's headline mode): a
  ``RemoteRollout`` yields group-complete ibatches while later groups are
  still generating on the elastic pool — training overlaps generation, the
  trainer-bubble time is measured and fed to the manager's adaptive
  balancer, which returns the next local-generation budget
  (stream_ray_trainer.py:691-704 ⇄ handlers.rs:867-901).
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import time
from typing import Any, Callable

import jax
import numpy as np

from polyrl_tpu import obs
from polyrl_tpu.data.batch import TensorBatch
from polyrl_tpu.models import decoder
from polyrl_tpu.ops import core_algos
from polyrl_tpu.rollout.engine import RolloutEngine
from polyrl_tpu.rollout.remote import RemoteRollout
from polyrl_tpu.rollout.sampling import SamplingParams
from polyrl_tpu.trainer.actor import ActorConfig, ReferencePolicy, StreamActor
from polyrl_tpu.trainer.critic import CriticConfig, StreamCritic
from polyrl_tpu.utils import checkpoint as ckpt_lib
from polyrl_tpu.utils.flops import FlopsCounter
from polyrl_tpu.utils.metrics import MetricsTracker, marked_timer

log = logging.getLogger(__name__)


class _ResultView:
    """Adapt a manager GenerateResult or a CBEngine output dict to the
    engine-output field names the assembly code consumes. Per-token
    ``weight_versions`` (which push version sampled each token — the
    training health ledger's staleness feed) ride along when the source
    carries them; an empty array means "unknown" and the assembled batch
    marks those tokens −1."""

    __slots__ = ("output_ids", "output_token_logprobs",
                 "output_token_weight_versions")

    def __init__(self, res):
        if isinstance(res, dict):
            ids, lps = res["token_ids"], res["logprobs"]
            wvs = res.get("weight_versions") or []
        else:
            ids, lps = res.output_token_ids, res.output_token_logprobs
            wvs = res.output_token_weight_versions or []
        self.output_ids = np.asarray(ids, np.int32)
        self.output_token_logprobs = np.asarray(lps, np.float32)
        self.output_token_weight_versions = np.asarray(wvs, np.int32)


@dataclasses.dataclass
class TrainerConfig:
    # batch accounting (reference names kept: SURVEY.md C1 batch checks)
    train_batch_size: int = 32            # prompts per step
    rollout_n: int = 4                    # samples per prompt
    ppo_mini_batch_size: int = 64         # trajectories per optimizer step
    micro_batch_size: int = 8             # trajectories per fwd/bwd
    min_stream_batch_size: int = 16       # ibatch granularity
    # lengths
    max_prompt_length: int = 128
    max_response_length: int = 128
    # packed-sequence (remove-padding) training + token-balanced micros
    # (reference use_remove_padding stream_dp_actor.py:41-47 and
    # prepare_dynamic_batch :35,136; recipe 16,384 tok/GPU): actor passes run
    # on fixed [n_rows, pack_len] packed grids instead of [B, Tp+Tr] pads
    use_remove_padding: bool = False
    pack_len: int = 0                     # 0 → max_prompt+max_response
    micro_token_budget: int = 0           # 0 → micro_batch_size rows
    # algorithm
    adv_estimator: str = "grpo"           # grpo | gae | rloo | reinforce_plus_plus | remax
    gamma: float = 1.0
    lam: float = 1.0
    use_kl_in_reward: bool = False
    kl_coef: float = 0.001
    kl_penalty: str = "kl"
    norm_adv_by_std_in_grpo: bool = True
    # weight push payload: "full" pushes the merged/plain tree;
    # "lora_delta" pushes ONLY the LoRA adapters (requires
    # actor.lora_rank > 0 and rollout workers serving --lora-rank) —
    # ~rank/hidden of the bytes per sync
    weight_sync: str = "full"
    # pipelined rollout (trainer/pipeline.py; ARCHITECTURE.md "Pipeline
    # overlap"): 0 = the serial loop, bitwise-identical to the pre-pipeline
    # behavior; N >= 1 lets a background lane generate up to N steps ahead
    # of training — rollouts then arrive weight-version stale
    # (see rollout_is_correction) and the per-step weight push goes async
    pipeline_depth: int = 0
    # bounded-staleness admission gate (ARCHITECTURE.md "Bounded-staleness
    # async training"): a prefetched stream may START while up to
    # staleness_limit-1 weight pushes are still in flight — i.e. against
    # any weight version within staleness_limit of the trainer's current
    # push version; only breaching the bound blocks the lane. 1 (default)
    # = the hard wait_pushed() fence (every push fully landed before the
    # next stream — the PR-3 pipeline, bitwise). >1 lets pushes overlap
    # generation MID-STREAM (the verify-before-install fabric makes a
    # half-landed push unobservable), so sequences legitimately span
    # versions and rollout_is_correction (REQUIRED then) applies
    # mixed-version per-token TIS keyed off rollout_weight_versions.
    staleness_limit: int = 1
    # truncated importance-sampling correction for stale rollouts: scale
    # advantages by min(exp(old_log_probs - rollout_log_probs),
    # rollout_is_cap) per token, keyed off each token's own behavior
    # version; unknown-version tokens (rollout_weight_versions == -1) are
    # excluded — weight 1.0 — and counted in
    # training/tis_unknown_version_tokens
    # (core_algos.mixed_version_importance_weights)
    rollout_is_correction: bool = False
    rollout_is_cap: float = 2.0
    # run
    total_steps: int = 10
    seed: int = 0
    # profiling (reference step-scoped profiling + nsight options,
    # SURVEY.md §5.1; TPU equivalent = jax.profiler traces)
    profile_steps: tuple = ()             # 1-based global steps to trace
    profile_dir: str = "/tmp/polyrl_profile"
    # validation (reference _validate + test_freq/val_before_train gates,
    # stream_ray_trainer.py:304-315,589-603; sample dump :585-587)
    test_freq: int = 0                    # validate every N steps (0 = off)
    val_before_train: bool = False
    val_temperature: float = 0.0          # greedy by default
    val_max_response_length: int = 0      # 0 → max_response_length
    rollout_data_dir: str = ""            # dump val generations as jsonl
    val_generations_to_log: int = 0       # echo first K generations to logger
    # checkpoint/resume (reference _save_checkpoint gating,
    # stream_ray_trainer.py:604-623; SURVEY.md §5.4)
    ckpt_dir: str | None = None
    save_freq: int = 0                    # 0 = only last step (+ESI)
    max_ckpt_keep: int = 3
    resume: str = "auto"                  # auto | disable
    esi_margin_s: float = 300.0
    # sampling
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0

    def __post_init__(self):
        if self.weight_sync not in ("full", "lora_delta"):
            raise ValueError(
                f"weight_sync must be 'full' or 'lora_delta', got "
                f"{self.weight_sync!r}")
        total = self.train_batch_size * self.rollout_n
        if total % self.ppo_mini_batch_size != 0:
            raise ValueError(
                f"total trajectories {total} not divisible by ppo_mini_batch_size"
                f" {self.ppo_mini_batch_size} (reference check main_stream.py:372-389)"
            )
        if self.ppo_mini_batch_size % self.micro_batch_size != 0:
            raise ValueError("mini batch not divisible by micro batch")
        if self.min_stream_batch_size % self.micro_batch_size != 0:
            raise ValueError("stream batch not divisible by micro batch")
        if self.pipeline_depth < 0:
            raise ValueError(
                f"pipeline_depth must be >= 0, got {self.pipeline_depth}")
        if self.staleness_limit < 1:
            raise ValueError(
                f"staleness_limit must be >= 1, got {self.staleness_limit}")
        if self.staleness_limit > 1 and self.pipeline_depth == 0:
            raise ValueError(
                f"staleness_limit={self.staleness_limit} requires the "
                f"pipelined trainer (pipeline_depth >= 1): the serial loop "
                f"has no async push to bound")
        if self.staleness_limit > 1 and not self.rollout_is_correction:
            # k>1 trains k versions off-policy; uncorrected that is
            # silently wrong, not a log line (the depth>0/limit=1 case
            # stays a warning — one version stale is the classic
            # one-step-off-policy regime)
            raise ValueError(
                f"staleness_limit={self.staleness_limit} without "
                f"rollout_is_correction: bounded-staleness rollouts train "
                f"up to {self.staleness_limit} weight versions off-policy "
                f"and MUST be importance-corrected — set "
                f"trainer.rollout_is_correction=true (and rollout_is_cap)")
        if self.rollout_is_cap <= 0:
            raise ValueError(
                f"rollout_is_cap must be > 0, got {self.rollout_is_cap}")
        if self.adv_estimator in ("grpo", "rloo") and (
            self.min_stream_batch_size % self.rollout_n != 0
        ):
            raise ValueError(
                "min_stream_batch_size must be a multiple of rollout_n so prompt"
                " groups are never split across ibatches (group-relative"
                " advantages would silently use partial groups)"
            )


class StreamRLTrainer:
    def __init__(
        self,
        cfg: TrainerConfig,
        actor: StreamActor,
        rollout: RolloutEngine,
        tokenizer,
        reward_manager,
        dataloader,
        critic: StreamCritic | None = None,
        ref_policy: ReferencePolicy | None = None,
        logger=None,
        val_dataset=None,
        recorder=None,
        health=None,
        autoscale=None,
    ):
        self.cfg = cfg
        self.actor = actor
        self.rollout = rollout
        self.tokenizer = tokenizer
        self.reward_manager = reward_manager
        self.dataloader = dataloader
        self.critic = critic
        self.ref_policy = ref_policy
        self.logger = logger
        self.val_dataset = val_dataset
        self.global_step = 0
        # multi-host SPMD: every process runs this same fit loop; process 0
        # owns the control plane (manager streaming, reward scoring, weight
        # fabric, logging) and broadcasts batches/scores to the others
        # (parallel/multihost.py; reference worker-group scatter,
        # stream_fsdp_workers.py:262-546)
        from polyrl_tpu.parallel import multihost
        self._mh = multihost
        self._is_main = multihost.is_main()
        self._multi = multihost.process_count() > 1
        # local-generation budget from the manager's balancer (None until the
        # first update_metrics round trip; manager default applies)
        self._max_local_gen_s: float | None = None
        # weight pushes initiated so far; a prefetched stream records the
        # count at its generation start, so the gap at consume time IS the
        # perf/weight_staleness gauge
        self._push_count = 0
        if cfg.pipeline_depth > 0 and not cfg.rollout_is_correction:
            log.warning(
                "pipeline_depth=%d without rollout_is_correction: rollouts "
                "arrive up to one weight-version stale and advantages are "
                "NOT importance-corrected", cfg.pipeline_depth)
        if cfg.adv_estimator == "gae" and critic is None:
            raise ValueError("GAE requires a critic")
        self._ckpt = (
            ckpt_lib.CheckpointManager(cfg.ckpt_dir, max_to_keep=cfg.max_ckpt_keep)
            if cfg.ckpt_dir
            else None
        )
        self._esi_expiry = ckpt_lib.esi_expiry_from_env()
        self._flops = FlopsCounter(actor.model_cfg, n_chips=jax.device_count())
        self._tracing = False
        # goodput accounting (obs/goodput.py): every step's wall time is
        # decomposed into non-overlapping phases; /statusz reads the
        # cumulative side
        self._goodput = obs.GoodputLedger(flops=self._flops)
        self._last_record: dict = {}
        self._statusz = None
        # critical-path plane (obs/critical_path.py): per-step extraction
        # over the span ring when tracing is on — critpath/* gauges, the
        # last N paths for critical_path.json bundles / fleet_report
        self._critpaths: collections.deque = collections.deque(maxlen=32)
        # fleet time-series rail (obs/timeseries.py): every finished step
        # record folds in; /statusz serves the windowed aggregates and
        # BalanceEstimator.trends() the autoscaling slopes
        self._timeseries = obs.TimeSeriesStore()
        # training health plane (obs/rlhealth.py): per-step RL-dynamics
        # ledger behind training/* step metrics and the /statusz training
        # section. Default-on (pass health=False to disable, or a
        # pre-built TrainingHealthLedger to configure tail sizes).
        if health is None:
            health = obs.TrainingHealthLedger()
        self._health = health or None
        # closed-loop autoscaling (rollout/autoscale.py): ticked once per
        # finished step with the fresh pool counters + the previous step's
        # record; also gates pipeline admission while the fleet is empty.
        # None (the default) is the pre-autoscale trainer, bit for bit.
        self._autoscale = autoscale
        # anomaly flight recorder (obs/recorder.py): fed each finished
        # step record; dumps post-mortem bundles on anomaly/crash
        self._recorder = recorder
        if recorder is not None and self._health is not None:
            # entropy-collapse/KL-blowup bundles carry the RL-dynamics
            # tail + the last batch's GRPO group table as training.json
            recorder.training_fn = self._health.bundle_view
        if recorder is not None:
            # stall/anomaly bundles carry the last N per-step critical
            # paths as critical_path.json (empty until tracing produces
            # one — the recorder then skips the file)
            recorder.critical_path_fn = self._critical_path_view
        if recorder is not None and isinstance(rollout, RemoteRollout):
            recorder.counters_fn = rollout.fault_counters
            # post-mortem bundles carry the fleet flight-deck tail (per-
            # engine occupancy/page pressure at anomaly time); resolved at
            # dump time — the pool may attach after construction
            recorder.engine_fn = (
                lambda: rollout.pool.engine_section()
                if rollout.pool is not None else {})
            # cold-frac / HBM-headroom anomaly bundles carry the fleet KV
            # memory plane (per-engine residency + headroom) as memory.json
            recorder.memory_fn = (
                lambda: rollout.pool.memory_section()
                if rollout.pool is not None else {})
            # device-frac / accounting-frac anomaly bundles carry the
            # fleet engine-loop profiler view (per-engine device-vs-host
            # split at anomaly time) as engine_profile.json; a
            # {"enabled": False} fleet (no engine reporting the profiler)
            # skips the file, mirroring memory_fn's empty-view semantics
            def _loop_profile_view():
                pool = rollout.pool
                if pool is None:
                    return {}
                section = pool.loop_profile_section()
                return section if section.get("enabled") else {}
            recorder.engine_profile_fn = _loop_profile_view

    # -- profiling (reference _start/_stop_profiling with continuous-step
    # logic, stream_ray_trainer.py:356-361,629-641) ----------------------

    def _profile_gate(self, about_to_run: int) -> None:
        """Start/stop jax.profiler traces so that consecutive profiled steps
        share one trace."""
        cfg = self.cfg
        want = about_to_run in cfg.profile_steps
        if want and not self._tracing:
            jax.profiler.start_trace(cfg.profile_dir)
            self._tracing = True
        elif not want and self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False

    # -- checkpoint/resume (reference stream_ray_trainer.py:305,604-623) --

    def _ckpt_state(self) -> dict:
        state = {"actor": {"params": self.actor.params,
                           "opt_state": self.actor.opt_state}}
        if self.critic is not None:
            state["critic"] = {"params": self.critic.params,
                               "opt_state": self.critic.opt_state}
        return state

    def _save_checkpoint(self) -> None:
        meta = {"global_step": self.global_step}
        if hasattr(self.dataloader, "state_dict"):
            meta["dataloader"] = self.dataloader.state_dict()
        self._ckpt.save(self.global_step, self._ckpt_state(), meta)

    def _load_checkpoint(self) -> bool:
        """Restore latest checkpoint if present; returns True on resume.
        Items are restored independently, so a critic-config change (actor-
        only ckpt into a critic trainer, or vice versa) resumes what
        matches instead of failing on pytree-structure mismatch."""
        if self._ckpt is None or self.cfg.resume == "disable":
            return False
        targets = {k: ckpt_lib.abstract_like(v)
                   for k, v in self._ckpt_state().items()}
        out = self._ckpt.restore(targets=targets)
        if out is None:
            return False
        state, meta = out
        if "actor" in state:
            self.actor.params = state["actor"]["params"]
            self.actor.opt_state = state["actor"]["opt_state"]
        if self.critic is not None and "critic" in state:
            self.critic.params = state["critic"]["params"]
            self.critic.opt_state = state["critic"]["opt_state"]
        self.global_step = int(meta.get("global_step", 0))
        if "dataloader" in meta and hasattr(self.dataloader, "load_state_dict"):
            self.dataloader.load_state_dict(meta["dataloader"])
        return True

    # -- rollout → TensorBatch -------------------------------------------

    def _prepare_prompts(self, records: list[dict]):
        """Unroll n samples per prompt (reference preprocess,
        sglang_rollout_remote.py:198-225)."""
        cfg = self.cfg
        prompts, gts, sources = [], [], []
        for rec in records:
            ids = self.tokenizer.encode(rec["prompt"])[: cfg.max_prompt_length]
            for _ in range(cfg.rollout_n):
                prompts.append(ids)
                gts.append(rec.get("ground_truth", ""))
                sources.append(rec.get("data_source", ""))
        return prompts, gts, sources

    def _sampling(self) -> SamplingParams:
        cfg = self.cfg
        return SamplingParams(
            temperature=cfg.temperature, top_p=cfg.top_p, top_k=cfg.top_k,
            max_new_tokens=cfg.max_response_length,
            stop_token_ids=(self.tokenizer.eos_token_id,),
        )

    def _assemble_batch(self, prompts, gts, sources, outs, group_ids) -> TensorBatch:
        """Reassemble fixed-shape arrays (the reference's postprocess,
        sglang_rollout_remote.py:318-391). ``outs`` expose ``output_ids`` and
        ``output_token_logprobs``; ``group_ids`` are batch-local dense ids."""
        cfg = self.cfg
        n = len(prompts)
        tp, tr = cfg.max_prompt_length, cfg.max_response_length
        pad = self.rollout.pad_token_id
        input_ids = np.full((n, tp + tr), pad, np.int32)
        attention_mask = np.zeros((n, tp + tr), np.float32)
        responses = np.full((n, tr), pad, np.int32)
        response_mask = np.zeros((n, tr), np.float32)
        rollout_log_probs = np.zeros((n, tr), np.float32)
        # which push version sampled each response token (−1 = unknown):
        # the health ledger's per-token staleness feed (obs/rlhealth.py)
        weight_versions = np.full((n, tr), -1, np.int32)
        for i, (p, o) in enumerate(zip(prompts, outs)):
            lp = len(p)
            input_ids[i, tp - lp : tp] = p
            attention_mask[i, tp - lp : tp] = 1.0
            r = np.asarray(o.output_ids[:tr])
            input_ids[i, tp : tp + len(r)] = r
            attention_mask[i, tp : tp + len(r)] = 1.0
            responses[i, : len(r)] = r
            response_mask[i, : len(r)] = 1.0
            rollout_log_probs[i, : len(r)] = np.asarray(
                o.output_token_logprobs[: len(r)])
            wv = np.asarray(getattr(o, "output_token_weight_versions", []))
            if len(wv) >= len(r) > 0:
                weight_versions[i, : len(r)] = wv[: len(r)]
        positions = np.maximum(attention_mask.cumsum(axis=-1) - 1, 0).astype(np.int32)

        return TensorBatch.from_dict(
            tensors={
                "input_ids": input_ids,
                "attention_mask": attention_mask,
                "positions": positions,
                "responses": responses,
                "response_mask": response_mask,
                "rollout_log_probs": rollout_log_probs,
                "rollout_weight_versions": weight_versions,
                "group_ids": np.asarray(group_ids, np.int32),
            },
            non_tensors={"ground_truth": list(gts), "data_source": list(sources)},
            meta_info={"global_step": self.global_step},
        )

    def _ibatch_iter(self, records: list[dict], rng, metrics: MetricsTracker):
        """Yield TensorBatch ibatches. Colocated: generate all, slice.
        Remote: stream group-complete chunks while generation continues.
        Multi-host: process 0 streams from the manager and broadcasts each
        ibatch; the other hosts replay the broadcast (their jitted updates
        then shard the same global batch over the mesh)."""
        yield from self._ibatch_fanout(
            lambda: self._ibatch_iter_local(records, rng, metrics), metrics)

    def _ibatch_fanout(self, make_local_iter: Callable, metrics: MetricsTracker):
        """Multi-host fan-out wrapper around a local ibatch source (either
        the direct ``_ibatch_iter_local`` stream or the pipeline's queue in
        pipelined mode — the broadcast collectives always run on THIS
        foreground thread so every process issues them in one order)."""
        if self._multi:
            if self._is_main:
                # error sentinel: if the control plane raises mid-stream the
                # other hosts must be released from their blocking collective
                # (they'd otherwise hang in broadcast_one_to_all forever)
                it = make_local_iter()
                while True:
                    try:
                        ib = next(it)
                    except StopIteration:
                        self._mh.broadcast_batch(("end", None))
                        return
                    except Exception as exc:
                        self._mh.broadcast_batch(("error", repr(exc)))
                        raise
                    with marked_timer("broadcast", metrics):
                        self._mh.broadcast_batch(("batch", ib))
                    yield ib
            else:
                while True:
                    with marked_timer("broadcast", metrics):
                        kind, ib = self._mh.broadcast_batch(None)
                    if kind == "end":
                        return
                    if kind == "error":
                        raise RuntimeError(f"main-process rollout failed: {ib}")
                    yield ib
            return
        yield from make_local_iter()

    def _ibatch_iter_local(self, records: list[dict], rng,
                           metrics: MetricsTracker):
        cfg = self.cfg
        prompts, gts, sources = self._prepare_prompts(records)
        if isinstance(self.rollout, RemoteRollout):
            stream = self.rollout.generate_stream(
                prompts, self._sampling(), group_size=cfg.rollout_n,
                min_emit=cfg.min_stream_batch_size,
                max_local_gen_s=self._max_local_gen_s)
            for chunk in stream:
                idxs = [i for i, _ in chunk]
                outs = [_ResultView(r) for _, r in chunk]
                raw_gids = np.asarray([i // cfg.rollout_n for i in idxs])
                _, dense = np.unique(raw_gids, return_inverse=True)
                yield self._assemble_batch(
                    [prompts[i] for i in idxs], [gts[i] for i in idxs],
                    [sources[i] for i in idxs], outs, dense)
        else:
            with marked_timer("gen", metrics):
                outs = self.rollout.generate(prompts, self._sampling(), rng=rng)
                outs = [o if hasattr(o, "output_ids") else _ResultView(o)
                        for o in outs]
            group_ids = np.repeat(np.arange(len(records), dtype=np.int32),
                                  cfg.rollout_n)
            batch = self._assemble_batch(prompts, gts, sources, outs, group_ids)
            yield from batch.split(cfg.min_stream_batch_size)

    def _push_weights(self, block: bool = True) -> None:
        """Push actor weights to the rollout plane. The push itself is
        control-plane (process 0 / no-op NullRollout elsewhere), but
        GATHERING cross-host-sharded params is collective — every host
        allgathers to host numpy first, or pack_params on process 0 would
        raise on non-addressable shards.

        ``block=False`` (pipelined mode): the version bump and the host
        gather still happen inline (the gather is collective, and the
        host copy detaches the payload from the actor's donated buffers),
        but the pack/wire round completes on a background thread — the
        pipeline's ``wait_pushed()`` fence joins it before the next
        generation stream (ARCHITECTURE.md "Pipeline overlap")."""
        params = self._gather_push_params()
        if not block and hasattr(self.rollout, "update_weights_async"):
            # snapshot to host NOW: the actor's next opt step donates the
            # param buffers, and the background pack must never read a
            # donated (deleted) buffer. Multi-host gathers already
            # produced host numpy; asarray is free there.
            params = jax.tree_util.tree_map(lambda x: np.asarray(x), params)
            self.rollout.update_weights_async(params)
        else:
            if not block:
                # pipelined COLOCATED engine without an async fabric: the
                # engine must own a copy — the prefetch lane generates
                # while the next step's update micros donate the actor's
                # param buffers (same rationale as RemoteRollout's
                # _update_local_copy)
                import jax.numpy as jnp

                params = jax.tree_util.tree_map(jnp.copy, params)
            self.rollout.update_weights(params)
        self._push_count += 1

    def _wait_pushed(self) -> None:
        """Fence on the last ``update_weights_async``: returns when its
        pack round has fully landed (no-op for synchronous rollouts)."""
        fn = getattr(self.rollout, "wait_pushed", None)
        if fn is not None:
            fn()

    def _wait_push_headroom(self, max_lag: int) -> None:
        """Bounded-staleness admission gate (``staleness_limit > 1``):
        block until at most ``max_lag`` async pushes are still in flight.
        Rollouts without a lag surface fall back to the full fence
        (conservative — lag 0 satisfies any bound)."""
        fn = getattr(self.rollout, "wait_push_lag", None)
        if fn is not None:
            fn(max_lag)
        else:
            self._wait_pushed()

    def _push_lag(self) -> int:
        """In-flight async push count (``perf/staleness_lag`` gauge)."""
        fn = getattr(self.rollout, "push_lag", None)
        return int(fn()) if fn is not None else 0

    def _gather_push_params(self):
        if self.cfg.weight_sync == "lora_delta":
            # delta sync: only the adapters ride the wire; workers hold the
            # frozen base and install a/b in place
            from polyrl_tpu.models import lora as lora_mod

            params = lora_mod.extract_adapters(self.actor.params)
            if self._multi:
                # gather ONLY the sharded adapter leaves; the alpha scalar
                # and base_stats are host-local replicated values that
                # process_allgather would stack/concat into wrong shapes
                from jax.experimental import multihost_utils as mhu

                params = dict(
                    params,
                    layers=jax.tree_util.tree_map(
                        lambda x: np.asarray(
                            mhu.process_allgather(x, tiled=True)),
                        params["layers"]),
                    base_stats=np.asarray(params["base_stats"]),
                    alpha=np.asarray(params["alpha"]))
            return params
        else:
            # export: LoRA actors merge adapters into the plain layout here
            # — the wire format and the engines never see wrapper nodes
            params = (self.actor.export_params()
                      if hasattr(self.actor, "export_params")
                      else self.actor.params)
        if self._multi:
            from jax.experimental import multihost_utils as mhu

            params = jax.tree_util.tree_map(
                lambda x: np.asarray(mhu.process_allgather(x, tiled=True)),
                params)
        return params

    def _to_host(self, x) -> np.ndarray:
        """jit output → host numpy. Multi-host: jitted outputs are GLOBAL
        arrays whose shards live on other processes; np.asarray would raise
        (non-addressable) — allgather the global value instead. The host-side
        advantage math then runs identically on every process."""
        if self._multi:
            from jax.experimental import multihost_utils as mhu

            return np.asarray(mhu.process_allgather(x, tiled=True))
        return np.asarray(x)

    # -- per-ibatch pipeline ---------------------------------------------

    def _process_ibatch(self, ibatch: TensorBatch, metrics: MetricsTracker) -> TensorBatch:
        """reward → old_logprob → ref → values → advantage (reference
        stream_ray_trainer.py:406-498)."""
        cfg = self.cfg
        with marked_timer("reward", metrics):
            # reward scoring is control-plane work (python scorers, possibly
            # remote reward endpoints): process 0 only, scores broadcast.
            # Errors broadcast too so non-main hosts fail fast instead of
            # hanging in the collective.
            err: Exception | None = None
            payload = None
            if self._is_main:
                try:
                    reward_out = self.reward_manager(ibatch)
                    payload = ("ok", (reward_out.token_level_scores,
                                      reward_out.metrics))
                except Exception as exc:  # noqa: BLE001 — re-raised below
                    err = exc
                    payload = ("error", repr(exc))
            if self._multi:
                payload = self._mh.broadcast_obj(payload)
            if payload[0] == "error":
                raise err if err is not None else RuntimeError(
                    f"main-process reward failed: {payload[1]}")
            token_level_scores, reward_metrics = payload[1]
            metrics.update(reward_metrics)
        if cfg.use_remove_padding:
            self._packed_logprob_pass(ibatch, metrics)
        else:
            feed = {k: ibatch[k] for k in
                    ("input_ids", "positions", "attention_mask", "responses",
                     "response_mask")}
            with marked_timer("old_log_prob", metrics):
                old_lp, entropy = self.actor.compute_log_prob(feed)
                ibatch.tensors["old_log_probs"] = self._to_host(old_lp)
                metrics.update({"actor/entropy_rollout": float(
                    core_algos.masked_mean(self._to_host(entropy),
                                           ibatch["response_mask"]))})
            if self.ref_policy is not None:
                with marked_timer("ref_log_prob", metrics):
                    ibatch.tensors["ref_log_probs"] = self._to_host(
                        self.ref_policy.compute_log_prob(feed))
        if self.critic is not None:
            with marked_timer("values", metrics):
                if cfg.use_remove_padding:
                    # packed values ride the same packs/gather specs as the
                    # logprob pass (reference packed critic,
                    # stream_dp_critic.py:35,83) — no padded [B, Tp+Tr]
                    # forward is ever built when the actor runs packed
                    vals = np.zeros((len(ibatch), cfg.max_response_length),
                                    np.float32)
                    for pack, spec in ibatch.meta_info["packs"]:
                        feed = {k: pack[k] for k in
                                ("input_ids", "positions", "attention_mask",
                                 "segment_ids", "loss_mask")}
                        spec.gather_into(
                            self._to_host(self.critic.compute_values_packed(feed)),
                            vals)
                    ibatch.tensors["values"] = vals
                else:
                    cfeed = {k: ibatch[k] for k in
                             ("input_ids", "positions", "attention_mask",
                              "responses", "response_mask")}
                    ibatch.tensors["values"] = self._to_host(
                        self.critic.compute_values(cfeed))

        with marked_timer("adv", metrics):
            token_scores = token_level_scores
            if cfg.use_kl_in_reward and "ref_log_probs" in ibatch:
                token_rewards, kl_mean = core_algos.apply_kl_penalty(
                    token_scores, ibatch["old_log_probs"], ibatch["ref_log_probs"],
                    ibatch["response_mask"], cfg.kl_coef, cfg.kl_penalty)
                token_rewards = np.asarray(token_rewards)
                metrics.update({"critic/kl_in_reward": float(kl_mean)})
            else:
                token_rewards = token_scores
            ibatch.tensors["token_level_rewards"] = token_rewards

            est = cfg.adv_estimator
            if est == "grpo":
                adv, ret = core_algos.compute_grpo_outcome_advantage(
                    token_rewards, ibatch["response_mask"], ibatch["group_ids"],
                    norm_adv_by_std=cfg.norm_adv_by_std_in_grpo,
                    num_groups=int(np.max(np.asarray(ibatch["group_ids"]))) + 1)
            elif est == "rloo":
                adv, ret = core_algos.compute_rloo_outcome_advantage(
                    token_rewards, ibatch["response_mask"], ibatch["group_ids"],
                    num_groups=int(np.max(np.asarray(ibatch["group_ids"]))) + 1)
            elif est == "reinforce_plus_plus":
                adv, ret = core_algos.compute_reinforce_plus_plus_outcome_advantage(
                    token_rewards, ibatch["response_mask"], cfg.gamma)
            elif est == "gae":
                adv, ret = core_algos.compute_gae_advantage_return(
                    token_rewards, ibatch["values"], ibatch["response_mask"],
                    cfg.gamma, cfg.lam)
            elif est == "remax":
                # baseline generation + scoring is control-plane (manager
                # stream + reward manager): process 0 computes, broadcasts
                baselines = (self._compute_remax_baselines(ibatch, metrics)
                             if self._is_main else None)
                if self._multi:
                    baselines = self._mh.broadcast_obj(baselines)
                adv, ret = core_algos.compute_remax_outcome_advantage(
                    token_rewards, baselines, ibatch["response_mask"])
            else:
                raise NotImplementedError(est)
            ibatch.tensors["advantages"] = np.asarray(adv)
            ibatch.tensors["returns"] = np.asarray(ret)
            tis_w = None
            tis_stats = None
            if cfg.rollout_is_correction:
                # stale-rollout correction (pipelined mode generates up to
                # staleness_limit weight-versions behind the update):
                # MIXED-VERSION per-token truncated importance reweighting
                # of each token's own behavior policy (rollout_log_probs,
                # captured under the version that sampled the token —
                # rollout_weight_versions) against the recomputed
                # current-policy old_log_probs — OPPO/LlamaRL's
                # bounded-staleness recipe. Unknown-version tokens (−1:
                # degraded local completions) are EXCLUDED (weight 1.0)
                # and counted, not corrected as if version-0.
                tis_w, _ratio, tis_stats = \
                    core_algos.mixed_version_importance_weights(
                        ibatch["old_log_probs"], ibatch["rollout_log_probs"],
                        ibatch["response_mask"],
                        ibatch.tensors.get("rollout_weight_versions"),
                        current_version=int(getattr(self.rollout,
                                                    "weight_version", 0)),
                        cap=cfg.rollout_is_cap)
                ibatch.tensors["advantages"] = (
                    ibatch.tensors["advantages"] * tis_w)
                metrics.update({
                    "actor/tis_weight_mean": tis_stats["mean_weight"],
                    "actor/tis_clip_frac": tis_stats["clip_frac"]})
        if self._health is not None:
            # RL-dynamics ledger feed (obs/rlhealth.py): everything is a
            # host array this pass already produced; the per-token
            # weight-version lag is measured against the rollout plane's
            # CURRENT push version (tokens at −1 = version unknown)
            self._health.observe_ibatch(
                advantages=np.asarray(ibatch["advantages"]),
                response_mask=np.asarray(ibatch["response_mask"]),
                group_ids=np.asarray(ibatch["group_ids"]),
                traj_rewards=np.asarray(token_rewards).sum(axis=-1),
                data_sources=ibatch["data_source"],
                old_log_probs=np.asarray(ibatch["old_log_probs"]),
                rollout_log_probs=np.asarray(ibatch["rollout_log_probs"]),
                tis_weights=tis_w,
                tis_stats=tis_stats,
                weight_versions=ibatch.tensors.get("rollout_weight_versions"),
                current_version=int(getattr(self.rollout,
                                            "weight_version", 0)),
                max_response_length=cfg.max_response_length)
        return ibatch

    # -- packed-sequence (remove-padding) path ---------------------------

    def _pack_geometry(self) -> tuple[int, int]:
        cfg = self.cfg
        pack_len = cfg.pack_len or (cfg.max_prompt_length + cfg.max_response_length)
        mesh = getattr(self.actor, "mesh", None)
        if mesh is not None:
            # packed × SP: the pack columns shard over sp (shard_map needs
            # even slices), and the rows over the batch axes — round both
            # up so any configured budget produces a shardable grid
            sp = mesh.shape.get("sp", 1)
            pack_len = -(-pack_len // sp) * sp
        if cfg.micro_token_budget > 0:
            n_rows = max(1, cfg.micro_token_budget // pack_len)
        else:
            n_rows = cfg.micro_batch_size
        if mesh is not None:
            # round DOWN (floor one full shard): rounding up could exceed
            # micro_token_budget — the HBM guard it exists to be
            rows_div = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
            if cfg.micro_token_budget > 0 and rows_div > n_rows:
                # the one-row-per-batch-shard floor would silently EXCEED
                # the budget (rows_div*pack_len > micro_token_budget):
                # that defeats the HBM guard, so fail loudly (advisor r5)
                raise ValueError(
                    f"micro_token_budget={cfg.micro_token_budget} cannot fit"
                    f" one packed row per batch shard: dp*fsdp={rows_div}"
                    f" rows x pack_len={pack_len} ="
                    f" {rows_div * pack_len} tokens minimum; raise the"
                    f" budget or shrink dp*fsdp/pack_len")
            n_rows = max(rows_div, n_rows // rows_div * rows_div)
        return pack_len, n_rows

    def _packed_logprob_pass(self, ibatch: TensorBatch,
                             metrics: MetricsTracker) -> None:
        """old/ref logprobs + entropy on the packed layout (the padded
        forward wastes FLOPs on pads — reference use_remove_padding), then
        gathered back to [B, Tr] for the (host-side) advantage math. The
        packs are stashed on the ibatch and reused for the update micros."""
        from polyrl_tpu.data import packing

        cfg = self.cfg
        pack_len, n_rows = self._pack_geometry()
        packs = list(packing.iter_packed_micros(
            ibatch, cfg.max_prompt_length, pack_len, n_rows,
            self.rollout.pad_token_id))
        ibatch.meta_info["packs"] = packs
        b, tr = len(ibatch), cfg.max_response_length
        old_lp = np.zeros((b, tr), np.float32)
        ref_lp = np.zeros((b, tr), np.float32) if self.ref_policy is not None else None
        ent_num = ent_den = 0.0
        with marked_timer("old_log_prob", metrics):
            for pack, spec in packs:
                feed = {k: pack[k] for k in
                        ("input_ids", "positions", "attention_mask",
                         "segment_ids", "loss_mask")}
                lp, ent = self.actor.compute_log_prob_packed(feed)
                spec.gather_into(self._to_host(lp), old_lp)
                lm = np.asarray(pack["loss_mask"])
                ent_num += float((self._to_host(ent) * lm).sum())
                ent_den += float(lm.sum())
        ibatch.tensors["old_log_probs"] = old_lp
        metrics.update({"actor/entropy_rollout": ent_num / max(ent_den, 1.0)})
        if ref_lp is not None:
            with marked_timer("ref_log_prob", metrics):
                for pack, spec in packs:
                    feed = {k: pack[k] for k in
                            ("input_ids", "positions", "attention_mask",
                             "segment_ids", "loss_mask")}
                    spec.gather_into(
                        self._to_host(
                            self.ref_policy.compute_log_prob_packed(feed)),
                        ref_lp)
            ibatch.tensors["ref_log_probs"] = ref_lp

    def _packed_micros(self, ibatch: TensorBatch):
        """Yield (packed_feed, n_trajectories) update micros, scattering the
        now-computed advantages/old/ref logprobs into each pack's layout."""
        packs = ibatch.meta_info["packs"]
        adv = np.asarray(ibatch["advantages"])
        old = np.asarray(ibatch["old_log_probs"])
        ref = (np.asarray(ibatch["ref_log_probs"])
               if "ref_log_probs" in ibatch else None)
        ret = (np.asarray(ibatch["returns"])
               if self.critic is not None and "returns" in ibatch else None)
        vals = (np.asarray(ibatch["values"])
                if self.critic is not None and "values" in ibatch else None)
        for pack, spec in packs:
            feed = {k: pack[k] for k in
                    ("input_ids", "positions", "attention_mask",
                     "segment_ids", "loss_mask")}
            feed["advantages"] = spec.scatter(adv)
            feed["old_log_probs"] = spec.scatter(old)
            if ref is not None:
                feed["ref_log_probs"] = spec.scatter(ref)
            if ret is not None:
                feed["returns"] = spec.scatter(ret)
            if vals is not None:
                feed["values"] = spec.scatter(vals)
            yield feed, len(spec.orig_idx)

    def _compute_remax_baselines(self, ibatch: TensorBatch,
                                 metrics: MetricsTracker) -> np.ndarray:
        """REMAX baseline (reference estimator enum stream_ray_trainer.py:50,
        377,387): ONE greedy rollout per prompt group, scored with the same
        reward manager; its score is the per-trajectory reward baseline."""
        cfg = self.cfg
        group_ids = np.asarray(ibatch["group_ids"])
        tp = cfg.max_prompt_length
        input_ids = np.asarray(ibatch["input_ids"])
        attn = np.asarray(ibatch["attention_mask"])
        gts, sources = ibatch["ground_truth"], ibatch["data_source"]
        uniq, first_idx = np.unique(group_ids, return_index=True)
        prompts = [input_ids[i, :tp][attn[i, :tp] > 0].tolist()
                   for i in first_idx]
        sampling = SamplingParams(
            temperature=0.0, top_p=1.0, top_k=0,
            max_new_tokens=cfg.max_response_length,
            stop_token_ids=(self.tokenizer.eos_token_id,))
        with marked_timer("remax_baseline", metrics):
            # nested: the outer generate_stream is still active — the
            # baseline stream must not pause/release the colocated engine
            outs, failed = self._generate_all(prompts, sampling, nested=True)
            base_batch = self._assemble_batch(
                prompts, [gts[i] for i in first_idx],
                [sources[i] for i in first_idx], outs,
                list(range(len(prompts))))
            base_scores = np.asarray(self.reward_manager(base_batch).scores,
                                     np.float32)
        if failed:
            # a greedy baseline hole would otherwise silently become
            # "baseline 0", biasing every advantage in the group upward.
            # Fall back to the group's sampled-reward mean (the RLOO-style
            # estimator) for exactly those groups, and surface a metric.
            log.warning("REMAX: %d/%d greedy baselines failed; substituting "
                        "group sampled-reward means", len(failed), len(prompts))
            traj_scores = np.asarray(
                ibatch["token_level_rewards"].sum(-1)
                if "token_level_rewards" in ibatch else
                self.reward_manager(ibatch).scores, np.float32)
            for fi in failed:
                base_scores[fi] = float(
                    np.mean(traj_scores[group_ids == uniq[fi]]))
        metrics.update({
            "reward/remax_baseline_mean":
                float(np.mean(base_scores)) if len(base_scores) else 0.0,
            "reward/remax_baseline_failed": float(len(failed)),
        })
        # expand group-level baselines to trajectory level
        group_to_score = {int(g): float(s) for g, s in zip(uniq, base_scores)}
        return np.asarray([group_to_score[int(g)] for g in group_ids],
                          np.float32)

    # -- validation (reference _validate, stream_ray_trainer.py:304-315) --

    def _generate_all(self, prompts: list[list[int]], sampling: SamplingParams,
                      nested: bool = False):
        """Generate for every prompt with either rollout flavour; returns
        ``(outputs, failed_indices)`` with outputs aligned with ``prompts``
        (failed slots hold an empty output). ``nested`` marks a call made
        while an outer generate_stream is active (REMAX baselines)."""
        if isinstance(self.rollout, RemoteRollout):
            outs: list = [None] * len(prompts)
            for chunk in self.rollout.generate_stream(
                    prompts, sampling, group_size=1, min_emit=len(prompts),
                    nested=nested):
                for i, res in chunk:
                    outs[i] = _ResultView(res)
            # dropped groups leave holes; substitute empty outputs and tell
            # the caller WHICH — silently zero-scoring them would skew
            # val means / REMAX baselines with no observable signal
            failed = [i for i, o in enumerate(outs) if o is None]
            empty = type("E", (), {"output_ids": np.zeros(0, np.int32),
                                   "output_token_logprobs": np.zeros(0, np.float32)})
            return [o if o is not None else empty for o in outs], failed
        outs = self.rollout.generate(prompts, sampling,
                                     rng=jax.random.PRNGKey(0))
        return [o if hasattr(o, "output_ids") else _ResultView(o) for o in outs], []

    def _validate(self) -> dict:
        """Greedy eval over the val dataset: per-data-source mean score +
        overall; optional generation dump (reference sample dump dir,
        stream_ray_trainer.py:585-587)."""
        cfg = self.cfg
        records = list(self.val_dataset)
        sampling = SamplingParams(
            temperature=cfg.val_temperature, top_p=1.0, top_k=0,
            max_new_tokens=cfg.val_max_response_length or cfg.max_response_length,
            stop_token_ids=(self.tokenizer.eos_token_id,),
        )
        per_source: dict[str, list[float]] = {}
        dump_rows: list[dict] = []
        num_failed = 0
        bs = max(cfg.train_batch_size, 1)
        for lo in range(0, len(records), bs):
            chunk = records[lo : lo + bs]
            prompts = [self.tokenizer.encode(r["prompt"])[: cfg.max_prompt_length]
                       for r in chunk]
            outs, failed = self._generate_all(prompts, sampling)
            num_failed += len(failed)
            gts = [r.get("ground_truth", "") for r in chunk]
            sources = [r.get("data_source", "") for r in chunk]
            batch = self._assemble_batch(prompts, gts, sources, outs,
                                         list(range(len(chunk))))
            reward_out = self.reward_manager(batch)
            failed_set = set(failed)
            for i, (src, sc) in enumerate(zip(sources, reward_out.scores)):
                # a failed generation is a HOLE, not a zero-score sample:
                # excluding it keeps val/test_score comparable across steps
                # with different failure counts (val/num_failed carries the
                # signal instead)
                if i in failed_set:
                    continue
                per_source.setdefault(src or "default", []).append(float(sc))
            if cfg.rollout_data_dir or cfg.val_generations_to_log:
                texts = self.tokenizer.batch_decode(
                    [np.asarray(o.output_ids) for o in outs],
                    skip_special_tokens=True)
                for r, txt, sc in zip(chunk, texts, reward_out.scores):
                    dump_rows.append({
                        "step": self.global_step, "prompt": r["prompt"],
                        "response": txt, "score": float(sc),
                        "ground_truth": r.get("ground_truth", ""),
                        "data_source": r.get("data_source", "")})
        metrics = {f"val/test_score/{src}": float(np.mean(v))
                   for src, v in per_source.items()}
        all_scores = [s for v in per_source.values() for s in v]
        metrics["val/test_score/mean"] = (
            float(np.mean(all_scores)) if all_scores else 0.0)
        metrics["val/num_failed"] = float(num_failed)
        if cfg.rollout_data_dir and dump_rows:
            import json
            import os

            os.makedirs(cfg.rollout_data_dir, exist_ok=True)
            path = os.path.join(cfg.rollout_data_dir,
                                f"val_step{self.global_step}.jsonl")
            with open(path, "w") as f:
                for row in dump_rows:
                    f.write(json.dumps(row) + "\n")
        if cfg.val_generations_to_log and self.logger is not None and dump_rows:
            for row in dump_rows[: cfg.val_generations_to_log]:
                self.logger.log({"val/generation": 0.0, **{
                    k: v for k, v in row.items() if isinstance(v, float)}},
                    step=self.global_step)
        return metrics

    def _maybe_validate(self, metrics: MetricsTracker, *, force: bool = False) -> None:
        cfg = self.cfg
        if self.val_dataset is None or not self._is_main:
            return
        due = force or (cfg.test_freq > 0 and self.global_step > 0
                        and self.global_step % cfg.test_freq == 0)
        if not due:
            return
        with marked_timer("testing", metrics):
            metrics.update(self._validate())

    # -- one training batch (stream → micros → opt steps) -----------------

    def _train_one_batch(self, ibatch_source: Callable,
                         metrics: MetricsTracker) -> dict:
        """Stream ibatches for one training batch through the per-ibatch
        pipeline and the cum-minibatch update micros (reference
        stream_ray_trainer.py:500-568); returns the stream-accounting
        state (``processed`` / ``n_tokens`` / ``bubble``).
        ``ibatch_source`` is a zero-arg callable returning the step's
        ibatch iterator — the direct ``_ibatch_iter`` in the serial loop,
        or the prefetch queue drain in pipelined mode."""
        cfg = self.cfg
        # stream accounting: ibatches arrive (possibly overlapping
        # generation); opt step when the cumulative trajectory count
        # crosses each minibatch boundary, plus a final flush on the last
        # micro so dropped groups never strand accumulated grads
        msize = cfg.ppo_mini_batch_size
        state = {"processed": 0, "n_tokens": 0, "bubble": 0.0}

        def micro_stream():
            it = ibatch_source()
            while True:
                wait_t0 = time.monotonic()
                try:
                    # the wait span is what the critical-path extractor
                    # attributes: covered by nested generation (serial) or
                    # the producer lane's prefetch span → generate;
                    # covered by nothing → a true bubble
                    with obs.span("trainer/ibatch_wait"):
                        ibatch = next(it)
                except StopIteration:
                    return
                # time blocked on rollout = the trainer bubble the
                # balancer minimizes (stream_ray_trainer.py:694-700)
                state["bubble"] += time.monotonic() - wait_t0
                ibatch = self._process_ibatch(ibatch, metrics)
                state["n_tokens"] += int(
                    np.asarray(ibatch["attention_mask"]).sum())
                if cfg.use_remove_padding:
                    yield from self._packed_micros(ibatch)
                else:
                    for m in ibatch.split(cfg.micro_batch_size):
                        yield m, len(m)

        def train_micro(micro, n_traj):
            # boundary-CROSSING, not exact multiples: ragged micro sizes
            # (packed micros, or streaming with adv estimators that allow
            # min_stream_batch_size % rollout_n != 0) may step over an
            # exact multiple and must still trigger the opt step
            prev = state["processed"]
            state["processed"] += n_traj
            is_opt = state["processed"] // msize > prev // msize
            # loss scale = the micro's trajectory share of the minibatch
            # (1/grad_steps for fixed micros; ragged micros still sum to
            # 1 over a full minibatch — reference loss_scale_factor)
            scale = n_traj / msize
            if isinstance(micro, dict):  # packed feed, actor-ready
                feed = micro
            else:
                feed = {k: micro[k] for k in (
                    "input_ids", "positions", "attention_mask", "responses",
                    "response_mask", "advantages", "old_log_probs")}
                if "ref_log_probs" in micro:
                    feed["ref_log_probs"] = micro["ref_log_probs"]
            with marked_timer("update_actor", metrics):
                m = self.actor.update_stream(feed, is_opt, loss_scale=scale)
                metrics.update({k: float(v) for k, v in m.items()})
            if self.critic is not None:
                if isinstance(micro, dict):  # packed feed: critic-ready
                    cfeed = micro
                else:
                    cfeed = {k: micro[k] for k in (
                        "input_ids", "positions", "attention_mask",
                        "responses", "response_mask", "returns", "values")}
                with marked_timer("update_critic", metrics):
                    cm = self.critic.update_stream(
                        cfeed, is_opt, loss_scale=scale)
                    metrics.update({k: float(v) for k, v in cm.items()})

        # micros train the moment they exist (never idle behind the
        # blocking ibatch wait); if a short batch (dropped groups) ends
        # mid-minibatch, flush the accumulated grads afterwards
        for micro, n_traj in micro_stream():
            train_micro(micro, n_traj)
        if state["processed"] % msize != 0 and state["processed"] > 0:
            metrics.update({k: float(v) for k, v in
                            self.actor.flush_opt_step().items()})
            if self.critic is not None:
                metrics.update({k: float(v) for k, v in
                                self.critic.flush_opt_step().items()})
        return state

    # -- live health plane (/statusz; obs/statusz.py) ---------------------

    def start_statusz(self, port: int = 0, host: str = "127.0.0.1"):
        """Mount the shared-schema ``/statusz`` exporter for this trainer
        process; returns the server (``.endpoint`` answers curl)."""
        from polyrl_tpu.obs.statusz import StatuszServer

        self._statusz = StatuszServer(self.statusz_snapshot,
                                      host=host, port=port).start()
        return self._statusz

    def stop_statusz(self) -> None:
        if self._statusz is not None:
            self._statusz.stop()
            self._statusz = None

    def statusz_snapshot(self) -> dict:
        """The trainer's side of the shared /statusz schema: current step,
        cumulative goodput phase breakdown, last-step histogram quantiles,
        fault/anomaly counters, weight staleness, pipeline queue depth."""
        from polyrl_tpu.obs import statusz

        rec = self._last_record
        counters: dict[str, float] = {}
        if isinstance(self.rollout, RemoteRollout):
            counters.update(self.rollout.fault_counters())
        if self._recorder is not None:
            counters.update(self._recorder.counters())
        gauges = {k: float(v) for k, v in rec.items()
                  if k.startswith(("perf/", "training/", "manager/",
                                   "pool/", "engine/", "critpath/",
                                   "autoscale/"))}
        pool = getattr(self.rollout, "pool", None)
        return statusz.build_snapshot(
            "trainer", step=self.global_step,
            goodput=self._goodput.snapshot(),
            histograms=statusz.nest_histograms(rec),
            counters=counters, gauges=gauges,
            queues={"pipeline_depth": float(self.cfg.pipeline_depth),
                    "staleness_limit": float(self.cfg.staleness_limit),
                    "pipeline_queue": float(rec.get(
                        "perf/pipeline_queue_depth", 0.0))},
            weights={"push_count": float(self._push_count),
                     "push_lag": float(self._push_lag()),
                     "version": float(getattr(self.rollout,
                                              "weight_version", 0)),
                     "staleness": float(rec.get(
                         "perf/weight_staleness", 0.0)),
                     # sharded-push plane (PR 15): stream fan-out width,
                     # slowest-stream bandwidth, resharded bytes, and
                     # per-stream resume count for the last rounds
                     "push_streams": counters.get(
                         "transfer/push_streams", 0.0),
                     "stream_bw_mbps_min": counters.get(
                         "transfer/stream_bw_mbps_min", 0.0),
                     "reshard_bytes": counters.get(
                         "transfer/reshard_bytes", 0.0),
                     "stream_resumes": counters.get(
                         "transfer/stream_resumes", 0.0)},
            pool=pool.statusz_section() if pool is not None else None,
            # fleet flight-deck aggregate (the rollout plane serves its own
            # per-engine ledger; the trainer serves the pool-wide view)
            engine=pool.engine_section() if pool is not None else None,
            # training health plane (always present on the trainer role
            # unless explicitly disabled with health=False)
            training=(self._health.snapshot()
                      if self._health is not None else None),
            # fleet time-series rail: windowed aggregates + slopes over
            # the step-record stream (obs/timeseries.py)
            timeseries=self._timeseries.section(),
            # closed-loop autoscaling plane: last decision + totals
            # (rollout/autoscale.py; empty when no controller attached)
            autoscale=(self._autoscale.statusz_section()
                       if self._autoscale is not None else None),
            # KV memory plane: fleet worst-case residency + headroom from
            # the pool sweep (the rollout plane serves its own ledger)
            memory=pool.memory_section() if pool is not None else None)

    def _critical_path_view(self) -> dict:
        """Recorder hook: the last N per-step critical paths, dumped into
        anomaly/stall bundles as ``critical_path.json`` (empty dict until
        tracing has produced one — the recorder then skips the file)."""
        if not self._critpaths:
            return {}
        return {"count": len(self._critpaths),
                "paths": list(self._critpaths)}

    def _wait_pool_admission(self, metrics=None) -> float:
        """Admission backpressure (degradation layer): before launching a
        new rollout stream, hold while the fleet is EMPTY (``active==0``)
        so a collapse window queues work instead of slamming every new
        stream straight into the tier-2 local-completion path. A no-op
        (0.0) without an AutoscaleController — the pre-autoscale trainer
        never waits. Returns seconds waited; gauges the wait when a
        metrics tracker is passed."""
        if self._autoscale is None:
            return 0.0
        waited = self._autoscale.hold_admission()
        if waited and metrics is not None:
            metrics.update_gauge(
                {"autoscale/admission_gate_wait_s": waited})
        return waited

    # -- fit --------------------------------------------------------------

    def fit(self) -> list[dict]:
        """Run ``total_steps`` PPO steps; returns per-step metric dicts."""
        cfg = self.cfg
        history = []
        base_rng = jax.random.PRNGKey(cfg.seed)
        resumed = self._load_checkpoint()
        if resumed and self.logger is not None:
            self.logger.log({"training/resumed_from_step": self.global_step},
                            step=self.global_step)
        # bootstrap weights into the rollout engine (reference fit :340)
        self._push_weights()
        if cfg.val_before_train and self.val_dataset is not None:
            pre = MetricsTracker()
            self._maybe_validate(pre, force=True)
            rec = pre.as_dict()
            history.append(rec)
            if self.logger is not None:
                self.logger.log(rec, step=self.global_step)

        # pipelined mode (cfg.pipeline_depth >= 1): a background lane
        # generates up to depth steps ahead while this thread trains —
        # see trainer/pipeline.py and ARCHITECTURE.md "Pipeline overlap".
        # The lane only runs where local production happens (process 0 /
        # single-host); other hosts keep replaying foreground broadcasts.
        pipeline = None
        if cfg.pipeline_depth > 0 and (not self._multi or self._is_main):
            from polyrl_tpu.trainer.pipeline import RolloutPipeline

            pipeline = RolloutPipeline(self, cfg.pipeline_depth,
                                       base_rng).start(
                self.global_step, cfg.total_steps)
        try:
            while self.global_step < cfg.total_steps:
                self._profile_gate(self.global_step + 1)
                metrics = MetricsTracker()
                step_t0 = time.monotonic()
                if pipeline is None and cfg.pipeline_depth > 0:
                    # non-main host of a pipelined run: ibatches arrive via
                    # the foreground broadcast plane exactly as in the
                    # serial loop
                    source = lambda: self._ibatch_fanout(None, metrics)  # noqa: E731
                elif pipeline is None:
                    records = next(self.dataloader)
                    # per-step rng derived from the step index so a resumed
                    # run replays the same sampling stream (keys need not be
                    # saved)
                    gen_rng = jax.random.fold_in(base_rng, self.global_step)
                    source = lambda: self._ibatch_iter(  # noqa: E731
                        records, gen_rng, metrics)
                else:
                    step = self.global_step
                    source = lambda: self._ibatch_fanout(  # noqa: E731
                        lambda: pipeline.step_ibatches(step, metrics),
                        metrics)

                # root span: every phase span, manager call, engine span,
                # and fabric push within the step shares this trace_id —
                # one step, one Perfetto timeline row group
                # (ARCHITECTURE.md "Observability")
                with obs.span("trainer/step", step=self.global_step + 1,
                              depth=cfg.pipeline_depth):
                    state = self._train_one_batch(source, metrics)
                    with marked_timer("update_weight", metrics):
                        # pipelined: version bump + host gather inline, the
                        # pack/wire round in the background — the pipeline
                        # fences on wait_pushed() before its next stream
                        self._push_weights(block=cfg.pipeline_depth == 0)
                # free optimizer HBM for the generation phase (colocated
                # time-slicing; no-op unless actor.cfg.offload_optimizer)
                self.actor.offload_opt_state()

                self.global_step += 1
                step_time = time.monotonic() - step_t0
                throughput = state["n_tokens"] / step_time if step_time else 0.0
                n_traj = max(state["processed"], 1)
                metrics.update({
                    "training/global_step": self.global_step,
                    "perf/step_time_s": step_time,
                    "perf/trainer_bubble_s": state["bubble"],
                    "perf/throughput_tokens_per_s": throughput,
                    "perf/throughput_tok_s_per_chip":
                        throughput / max(jax.device_count(), 1),
                    "perf/rollout_throughput_tok_s":
                        self.rollout.last_gen_throughput,
                })
                metrics.update(self._flops.step_metrics(
                    state["n_tokens"], state["n_tokens"] / n_traj, step_time))
                if isinstance(self.rollout, RemoteRollout):
                    # control-plane fault counters (supervisor restarts,
                    # client retries, stream resumes): cumulative gauges,
                    # visible every step so a chaos event is observable in
                    # the step record
                    metrics.update_gauge(self.rollout.fault_counters())
                    # balancer feed: raw scalars PLUS the goodput phase
                    # walls the progressive estimator windows over —
                    # generate (colocated gen) and update (actor+critic),
                    # the two walls whose ratio decides how much
                    # generation the trainer's update window can hide
                    timings = metrics.timings()
                    step_stats = dict(
                        step_time_s=step_time,
                        trainer_bubble_s=state["bubble"],
                        throughput=throughput,
                        generate_s=float(timings.get("gen", 0.0)),
                        update_s=float(timings.get("update_actor", 0.0))
                        + float(timings.get("update_critic", 0.0)),
                        # fleet occupancy from the previous step's pool
                        # aggregation: the balance estimator's trend input
                        # (pool/balance_occupancy_slope)
                        occupancy=float(self._last_record.get(
                            "engine/occupancy", 0.0)),
                        # fleet-min engine-loop device fraction (same lag):
                        # host-bound engines must not read as "add more"
                        device_frac=float(self._last_record.get(
                            "engine/device_frac", 0.0)))
                    if pipeline is not None:
                        # scrape + balancer round-trip ride the pipeline
                        # thread (off the hot path); their gauges land in
                        # the next consumed step's record
                        pipeline.submit_step_stats(**step_stats)
                    else:
                        # per-step scrape of the manager's /metrics: pool
                        # health + queue depths + request totals land in the
                        # step record as manager/* gauges (no separate
                        # Prometheus needed)
                        metrics.update_gauge(
                            self.rollout.scrape_manager_metrics())
                        # actuating metrics: the balancer returns the next
                        # local-generation budget (handlers.rs:867-901)
                        resp = self.rollout.update_metrics(**step_stats)
                        if resp.get("max_local_gen_s"):
                            self._max_local_gen_s = float(
                                resp["max_local_gen_s"])
                            metrics.update({
                                "training/max_local_gen_s":
                                    self._max_local_gen_s,
                                "training/num_rollout_instances":
                                    float(resp.get("num_instances", 0))})
                    # what the balancer actually saw (windowed medians +
                    # offload fraction) and, with a PoolManager attached,
                    # the pool membership counters — pool/* gauges in
                    # every step record
                    metrics.update_gauge(self.rollout.balance.metrics())
                    if self.rollout.pool is not None:
                        pool_counters = self.rollout.pool.counters()
                        metrics.update_gauge(pool_counters)
                        if self._autoscale is not None:
                            # close the loop: the controller reads this
                            # step's fleet gauges + the PREVIOUS record's
                            # critpath attribution and acts on the pool;
                            # its decision lands in THIS record
                            metrics.update_gauge(self._autoscale.tick(
                                self.global_step, fleet=pool_counters,
                                record=self._last_record))
                self._maybe_validate(metrics,
                                     force=self.global_step >= cfg.total_steps)
                if self._ckpt is not None and ckpt_lib.should_save_checkpoint(
                    self.global_step, cfg.total_steps, cfg.save_freq,
                    esi_expiry_ts=self._esi_expiry,
                    esi_margin_s=cfg.esi_margin_s,
                ):
                    with marked_timer("save_checkpoint", metrics):
                        self._save_checkpoint()
                # distribution roll-up: drain the process-global histogram
                # registry (rollout latency / decode rate, transfer push,
                # manager RTT — observed by components with no tracker
                # handle) into this step's record as p50/p95/p99/max.
                # Drained BEFORE goodput accounting so the ledger can
                # attribute the resume-wait / manager-RTT totals.
                hists = obs.drain_histograms()
                # goodput attribution (obs/goodput.py): the FULL step wall
                # (incl. validation + checkpoint IO, which perf/step_time_s
                # predates) decomposed into non-overlapping goodput/* phases
                gp = self._goodput.account(
                    step_time_s=time.monotonic() - step_t0,
                    timings=metrics.timings(),
                    bubble_s=state["bubble"],
                    overlap_s=metrics.get("perf/pipeline_overlap_s"),
                    histograms=hists,
                    n_tokens=state["n_tokens"],
                    mean_context_len=state["n_tokens"] / n_traj,
                    n_chips=jax.device_count())
                metrics.update(gp)
                metrics.merge_histograms(hists)
                tracer = obs.get_tracer()
                if tracer.enabled:
                    # critical-path attribution over the step's span tree:
                    # which segment actually bounded the wall, and how much
                    # a 10% speedup there would buy (critpath/* gauges;
                    # obs/critical_path.py). Windowed to the goodput wall so
                    # validation/checkpoint time attributes as housekeeping.
                    cp = obs.extract_critical_path(
                        tracer.records(), step=self.global_step,
                        wall_s=gp["goodput/step_wall_s"])
                    if cp is not None:
                        metrics.update_gauge(cp.metrics())
                        self._critpaths.append(cp.to_dict())
                if self._health is not None:
                    # training health plane: close the step's RL-dynamics
                    # window — training/* gauges (group diagnostics,
                    # staleness, actor mirrors) + distribution histograms
                    # land in this record; the recorder watches the
                    # direction-aware keys off the same record
                    hg, hh = self._health.finalize_step(
                        self.global_step, metrics)
                    metrics.update_gauge(hg)
                    metrics.merge_histograms(hh)
                if self.logger is not None:
                    metrics.update_gauge({"obs/log_errors": float(
                        getattr(self.logger, "log_errors", 0))})
                if self._recorder is not None:
                    # one step of lag by design: the gauges describe the
                    # steps already watched when this record was built
                    metrics.update_gauge(self._recorder.counters())
                record = metrics.as_dict()
                history.append(record)
                self._last_record = record
                # time-series rail: the bounded per-key ring behind the
                # /statusz "timeseries" section (windowed aggregates +
                # slopes — the fleet trend surface autoscaling reads)
                self._timeseries.observe(self.global_step, record)
                if self._recorder is not None:
                    # anomaly watch over the live step stream; a spike in
                    # step time (or a throughput collapse) dumps a
                    # post-mortem bundle into the run dir
                    self._recorder.record_step(self.global_step, record)
                if self.logger is not None and self._is_main:
                    self.logger.log(record, step=self.global_step)
        except BaseException as exc:
            if self._recorder is not None:
                # crash post-mortem: the bundle carries the trace ring and
                # every thread's stack at the moment of death
                self._recorder.dump(f"crash-{type(exc).__name__}",
                                    detail=repr(exc), step=self.global_step)
            raise
        finally:
            if pipeline is not None:
                pipeline.close()
        # drain the last async push before teardown can stop the sender
        self._wait_pushed()
        self._profile_gate(-1)  # close any open trace
        tracer = obs.get_tracer()
        if tracer.enabled and self._is_main:
            # per-run Perfetto dump next to the JSONL metrics (spans.jsonl
            # + trace.json); no-op when no out_dir is configured
            tracer.export_run()
        if self._ckpt is not None:
            self._ckpt.wait()
        return history
