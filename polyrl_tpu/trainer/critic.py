"""Stream PPO critic: token-value model with stream update semantics.

Equivalent of the reference's C9 ``StreamDataParallelPPOCritic``
(``stream_dp_critic.py:49-141``): value loss with clipping
(``compute_value_loss``), gradient accumulation scaled by loss_scale, opt
step on ``is_opt_step``. The value model is the decoder trunk with a scalar
head instead of the LM head.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import optax

from polyrl_tpu.models import decoder
from polyrl_tpu.ops import core_algos


@dataclasses.dataclass(frozen=True)
class CriticConfig:
    cliprange_value: float = 0.5
    loss_agg_mode: str = "token-mean"
    lr: float = 1e-5
    weight_decay: float = 0.01
    max_grad_norm: float = 1.0
    remat: bool = True


def init_critic_params(rng: jax.Array, model_cfg: decoder.ModelConfig) -> dict:
    params = decoder.init_params(rng, model_cfg)
    params.pop("lm_head", None)
    params["value_head"] = (
        jax.random.normal(jax.random.fold_in(rng, 7), (model_cfg.hidden_size, 1), jnp.float32)
        * 0.01
    ).astype(model_cfg.dtype)
    return params


def critic_param_specs(model_cfg: decoder.ModelConfig) -> dict:
    from jax.sharding import PartitionSpec as P

    specs = decoder.param_specs(model_cfg)
    specs.pop("lm_head", None)
    specs["value_head"] = P(None, None)
    return specs


def forward_values(params, model_cfg, input_ids, positions, attn_mask, responses,
                   remat, attn_fn=None, layers_fn=None):
    """Token values for the response region [B, T_resp] (f32)."""
    # trunk forward: reuse decoder but skip the LM head by computing
    # hidden states via a value-head projection on the normed trunk output.
    value_params = dict(params)
    head = value_params.pop("value_head")
    # decoder.forward computes logits = x @ head; give it the value head as a
    # [D, 1] lm_head so XLA never materialises the [B, T, V] logits.
    value_params["lm_head"] = head
    cfg = dataclasses.replace(model_cfg, tie_word_embeddings=False)
    values, _ = decoder.forward(value_params, cfg, input_ids, positions,
                                attn_mask, remat=remat, attn_fn=attn_fn,
                                layers_fn=layers_fn)
    t_resp = responses.shape[1]
    return values[:, -t_resp - 1 : -1, 0].astype(jnp.float32)


def forward_values_packed(params, model_cfg, input_ids, positions, attn_mask,
                          segment_ids, remat, loss_mask=None, attn_fn=None,
                          layers_fn=None):
    """Per-column values [R, L] on the packed (remove-padding) layout
    (reference packed critic, stream_dp_critic.py:35,83): column t holds the
    value predicted from column t-1 — the same one-left shift as
    ``forward_values`` and the packed logprob pass, so the caller's
    loss_mask/gather spec selects response-token values directly.
    ``loss_mask`` zeroes columns outside the mask (finiteness guard, same
    double-where rationale as the actor's packed pass). ``attn_fn``:
    optional segment-aware SP attention (see the actor's packed pass)."""
    from polyrl_tpu.trainer.actor import bind_packed_attention

    attn, lf = bind_packed_attention(attn_fn, layers_fn, segment_ids)
    value_params = dict(params)
    head = value_params.pop("value_head")
    value_params["lm_head"] = head
    cfg = dataclasses.replace(model_cfg, tie_word_embeddings=False)
    values, _ = decoder.forward(value_params, cfg, input_ids, positions,
                                attn_mask, remat=remat, attn_fn=attn,
                                layers_fn=lf)
    v = values[:, :-1, 0].astype(jnp.float32)
    v = jnp.pad(v, ((0, 0), (1, 0)))
    if loss_mask is not None:
        v = jnp.where(loss_mask > 0, v, 0.0)
    return v


class StreamCritic:
    def __init__(self, model_cfg: decoder.ModelConfig, cfg: CriticConfig,
                 params: Any, mesh=None, attn_fn=None, layers_fn=None,
                 packed_attn_fn=None):
        from polyrl_tpu.trainer.actor import default_train_attention

        self.model_cfg = model_cfg
        self.cfg = cfg
        self.mesh = mesh
        self.attn_fn = attn_fn if attn_fn is not None else default_train_attention()
        self.layers_fn = layers_fn  # pipeline-parallel layer stack (pp > 1)
        self.packed_attn_fn = packed_attn_fn  # see StreamActor
        if mesh is not None:
            # backbone leaves follow decoder.param_specs; critic-only leaves
            # (the [D, 1] value head) fall back to replicated
            from polyrl_tpu.parallel import mesh as meshlib

            params = meshlib.shard_params(mesh, params,
                                          decoder.param_specs(model_cfg))
        self.params = params
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.max_grad_norm),
            optax.adamw(cfg.lr, weight_decay=cfg.weight_decay),
        )
        self.opt_state = self.optimizer.init(params)
        self.accum_grads = jax.tree_util.tree_map(jnp.zeros_like, params)
        self._accum_scale = 0.0  # see StreamActor: tail-flush renormalization
        self._update_fns: dict = {}
        self._value_fn = None

    def _loss(self, params, batch, loss_scale):
        if "segment_ids" in batch:  # packed (remove-padding) layout
            vpreds = forward_values_packed(
                params, self.model_cfg, batch["input_ids"],
                batch["positions"], batch["attention_mask"],
                batch["segment_ids"], self.cfg.remat,
                loss_mask=batch["loss_mask"], attn_fn=self.packed_attn_fn,
                layers_fn=self.layers_fn,
            )
            mask = batch["loss_mask"]
        else:
            vpreds = forward_values(
                params, self.model_cfg, batch["input_ids"], batch["positions"],
                batch["attention_mask"], batch["responses"], self.cfg.remat,
                attn_fn=self.attn_fn, layers_fn=self.layers_fn,
            )
            mask = batch["response_mask"]
        vf_loss, clipfrac = core_algos.compute_value_loss(
            vpreds, batch["returns"], batch["values"], mask,
            cliprange_value=self.cfg.cliprange_value,
            loss_agg_mode=self.cfg.loss_agg_mode,
        )
        return vf_loss * loss_scale, {"critic/vf_loss": vf_loss, "critic/vf_clipfrac": clipfrac}

    def _build_update(self, is_opt_step: bool):
        optimizer = self.optimizer

        def update(params, opt_state, accum, batch, loss_scale):
            (loss, metrics), grads = jax.value_and_grad(self._loss, has_aux=True)(
                params, batch, loss_scale
            )
            accum = jax.tree_util.tree_map(jnp.add, accum, grads)
            if is_opt_step:
                updates, opt_state = optimizer.update(accum, opt_state, params)
                params = optax.apply_updates(params, updates)
                metrics = dict(metrics)
                metrics["critic/grad_norm"] = optax.global_norm(accum)
                accum = jax.tree_util.tree_map(jnp.zeros_like, accum)
            return params, opt_state, accum, loss, metrics

        return jax.jit(update, donate_argnums=(0, 1, 2))

    def _shard_feed(self, batch: dict) -> dict:
        if self.mesh is None:
            return batch
        from polyrl_tpu.parallel import mesh as meshlib

        return meshlib.shard_batch(self.mesh, batch)

    def update_stream(self, batch: dict, is_opt_step: bool, loss_scale: float = 1.0) -> dict:
        batch = self._shard_feed(batch)
        if is_opt_step not in self._update_fns:
            self._update_fns[is_opt_step] = self._build_update(is_opt_step)
        self.params, self.opt_state, self.accum_grads, _, metrics = self._update_fns[is_opt_step](
            self.params, self.opt_state, self.accum_grads, batch,
            jnp.asarray(loss_scale, jnp.float32),
        )
        self._accum_scale = 0.0 if is_opt_step else self._accum_scale + loss_scale
        return metrics

    def flush_opt_step(self) -> dict:
        """Apply accumulated grads without new data (see StreamActor);
        renormalizes by the summed loss_scale so the partial minibatch's
        effective gradient scale matches a full one."""
        if not hasattr(self, "_flush_fn"):
            optimizer = self.optimizer

            def flush(params, opt_state, accum, inv_scale):
                accum = jax.tree_util.tree_map(lambda g: g * inv_scale, accum)
                updates, opt_state = optimizer.update(accum, opt_state, params)
                params = optax.apply_updates(params, updates)
                gn = optax.global_norm(accum)
                accum = jax.tree_util.tree_map(jnp.zeros_like, accum)
                return params, opt_state, accum, gn

            self._flush_fn = jax.jit(flush, donate_argnums=(0, 1, 2))
        inv = 1.0 / self._accum_scale if self._accum_scale > 0 else 1.0
        self.params, self.opt_state, self.accum_grads, gn = self._flush_fn(
            self.params, self.opt_state, self.accum_grads,
            jnp.asarray(inv, jnp.float32))
        self._accum_scale = 0.0
        return {"critic/grad_norm": gn}

    def compute_values(self, batch: dict) -> jnp.ndarray:
        batch = self._shard_feed(batch)
        if self._value_fn is None:
            self._value_fn = jax.jit(
                lambda p, b: forward_values(
                    p, self.model_cfg, b["input_ids"], b["positions"],
                    b["attention_mask"], b["responses"], False,
                    attn_fn=self.attn_fn, layers_fn=self.layers_fn,
                )
            )
        return self._value_fn(self.params, batch)

    def compute_values_packed(self, batch: dict) -> jnp.ndarray:
        """[R, L] per-column values on a packed feed (no grad)."""
        batch = self._shard_feed(batch)
        if not hasattr(self, "_value_fn_packed"):
            self._value_fn_packed = jax.jit(
                lambda p, b: forward_values_packed(
                    p, self.model_cfg, b["input_ids"], b["positions"],
                    b["attention_mask"], b["segment_ids"], False,
                    loss_mask=b.get("loss_mask"),
                    attn_fn=self.packed_attn_fn,
                    layers_fn=self.layers_fn,
                )
            )
        return self._value_fn_packed(self.params, batch)
