"""RolloutPipeline — one-step-ahead asynchronous rollout production.

The serial ``fit`` loop leaves the rollout plane idle through every
update phase and the trainer idle through every generation ramp (the
pipelining result in OPPO arxiv 2509.25762 / LlamaRL arxiv 2505.24034;
ARCHITECTURE.md "Pipeline overlap"). This object splits the step into two
lanes:

- **producer lane** (one background thread, named ``rollout-pipeline``):
  pulls the next batch of records from the dataloader (the 1-deep host-side
  data prep prefetch), derives the per-step rng, and drives the trainer's
  ``_ibatch_iter_local`` stream for up to ``depth`` steps ahead of training,
  pushing assembled ibatches into a bounded queue. Before each step's first
  generation request it takes the bounded-staleness ADMISSION GATE
  (``trainer.staleness_limit``; ARCHITECTURE.md "Bounded-staleness async
  training"): with the default limit 1 this is the hard ``wait_pushed()``
  fence — a stream never races a half-landed weight push; with limit k>1
  the stream may start while up to k-1 pushes are still in flight
  (``wait_push_lag(k-1)``) — generation then overlaps pushes MID-STREAM
  (safe: receivers verify-before-install), sequences legitimately span
  weight versions, and mixed-version per-token TIS corrects the
  off-policyness at update time. The per-step manager ``/metrics`` scrape
  and the ``update_metrics`` balancer round-trip also run here, off the
  foreground hot path.
- **consumer lane** (the trainer's foreground thread): drains the queue via
  :meth:`step_ibatches` and runs reward → logprob → advantage → update as
  today. In multi-host runs the foreground re-broadcasts each ibatch, so
  jax collectives keep a single, identical issue order on every process —
  the producer lane is strictly control-plane + generation.

Flow control is a step-credit semaphore: the producer needs one credit per
step and the consumer grants one when it *starts* a step, so the producer
runs at most ``depth`` steps ahead of the step being trained; within a
step the bounded queue gives item-level backpressure. Staleness follows:
with ``depth=1`` a stream launched mid-step-N generates with the weights of
step N-1 — one version stale — which ``rollout_is_correction`` compensates
with truncated importance reweighting (ops/core_algos.py).

Errors on either lane propagate: a producer failure is queued as a sentinel
and re-raised on the foreground (whose multi-host wrapper broadcasts it to
every process); a consumer failure closes the pipeline, which unblocks a
producer parked on the queue or the credit semaphore and joins the thread.
"""

from __future__ import annotations

import logging
import queue
import threading
import time

from polyrl_tpu import obs
from polyrl_tpu.utils.metrics import MetricsTracker

log = logging.getLogger(__name__)


class PipelineClosed(RuntimeError):
    """The pipeline stopped without finishing the requested step."""


class RolloutPipeline:
    def __init__(self, trainer, depth: int, base_rng):
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self.trainer = trainer
        self.depth = depth
        self.base_rng = base_rng
        cfg = trainer.cfg
        per_step = max(
            1, -(-cfg.train_batch_size * cfg.rollout_n
                 // max(cfg.min_stream_batch_size, 1)))
        # depth+1 steps may be in flight (the one being trained plus depth
        # prefetched); +depth+2 covers the end sentinels without ever
        # blocking a producer that the credit gate already admitted
        self._q: queue.Queue = queue.Queue(
            maxsize=(self.depth + 1) * per_step + self.depth + 2)
        self._credits = threading.Semaphore(self.depth)
        self._stats_q: queue.Queue = queue.Queue()
        self._gauges: dict[str, float] = {}
        self._gauges_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # producer spans adopt the fit-level context so the prefetch lane
        # shows up in the same Perfetto trace (its own tid = its own track)
        self._trace_ctx = obs.get_tracer().capture()

    # -- lifecycle ----------------------------------------------------------

    def start(self, start_step: int, total_steps: int) -> "RolloutPipeline":
        self._thread = threading.Thread(
            target=self._run, args=(start_step, total_steps),
            name="rollout-pipeline", daemon=True)
        self._thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Stop the producer and join it. Safe to call from the foreground's
        error path: a producer blocked on the queue or the credit gate polls
        the stop flag and exits; an abandoned generate_stream generator's
        own ``finally`` releases any engine resources it held."""
        self._stop.set()
        self._credits.release()  # unblock a producer parked on the gate
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
            if t.is_alive():
                log.warning("rollout-pipeline thread did not stop in %.0fs",
                            timeout)

    # -- producer lane ------------------------------------------------------

    def _run(self, start_step: int, total_steps: int) -> None:
        import jax

        trainer = self.trainer
        with obs.get_tracer().adopt(self._trace_ctx):
            for step in range(start_step, total_steps):
                if not self._acquire_credit():
                    return
                # off-hot-path control-plane work between streams: manager
                # /metrics scrape + balancer update_metrics for any step the
                # foreground finished since the last stream started
                self._drain_stats()
                prod_metrics = MetricsTracker()
                try:
                    # degradation backpressure (rollout/autoscale.py): while
                    # the fleet is EMPTY, hold the new stream instead of
                    # slamming it straight into the tier-2 local-completion
                    # path — a no-op without an AutoscaleController
                    trainer._wait_pool_admission(prod_metrics)
                    # admission gate: limit=1 is the hard fence (the
                    # previous async push fully landed before this
                    # stream's first request — today's bitwise behavior);
                    # limit=k>1 only blocks when k-1 pushes are already in
                    # flight, so generation overlaps the pack/wire walls
                    limit = max(int(getattr(trainer.cfg,
                                            "staleness_limit", 1)), 1)
                    t_fence = time.monotonic()
                    if limit <= 1:
                        trainer._wait_pushed()
                    else:
                        trainer._wait_push_headroom(limit - 1)
                    gate_wait = time.monotonic() - t_fence
                    prod_metrics.add_timing("prefetch_fence", gate_wait)
                    prod_metrics.update(
                        {"perf/staleness_gate_wait_s": gate_wait})
                    prod_metrics.update_gauge({
                        "perf/staleness_lag": float(trainer._push_lag()),
                        "perf/staleness_limit": float(limit)})
                    version = trainer._push_count
                    gen_t0 = time.monotonic()
                    with obs.span("trainer/prefetch", step=step + 1,
                                  version=version):
                        records = next(trainer.dataloader)
                        rng = jax.random.fold_in(self.base_rng, step)
                        for ib in trainer._ibatch_iter_local(
                                records, rng, prod_metrics):
                            if not self._put(("ibatch", step, ib)):
                                return
                except BaseException as exc:  # noqa: BLE001 — re-raised on
                    # the foreground (and broadcast to non-main hosts there)
                    log.exception("rollout pipeline producer failed at "
                                  "step %d", step + 1)
                    self._put(("error", step, exc))
                    return
                self._put(("end", step, {
                    "gen_t0": gen_t0, "gen_t1": time.monotonic(),
                    "weight_version": version, "metrics": prod_metrics}))

    def _acquire_credit(self) -> bool:
        while not self._stop.is_set():
            if self._credits.acquire(timeout=0.2):
                return True
        return False

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer lane ------------------------------------------------------

    def step_ibatches(self, step: int, metrics: MetricsTracker):
        """Yield the ibatches of ``step`` from the queue; on the step's end
        sentinel, fold the producer's metrics plus the overlap/staleness/
        queue-depth gauges into ``metrics`` and return. Granting the step
        credit HERE (at consume start) is what lets the producer run ahead
        while this step trains."""
        self._credits.release()
        consume_t0 = time.monotonic()
        while True:
            item = self._get()
            kind, item_step, payload = item
            if kind == "error":
                raise payload
            if item_step != step:
                raise PipelineClosed(
                    f"pipeline out of sync: expected step {step + 1}, got "
                    f"{item_step + 1} (a previous step was abandoned "
                    f"mid-stream)")
            if kind == "end":
                # overlap = the slice of this step's generation that had
                # already happened before the foreground even began the
                # step — the serial loop's per-step gain
                overlap = max(0.0, min(payload["gen_t1"], consume_t0)
                              - payload["gen_t0"])
                metrics.update({"perf/pipeline_overlap_s": overlap})
                metrics.update_gauge({
                    "perf/pipeline_queue_depth": float(self._q.qsize()),
                    "perf/weight_staleness": float(
                        self.trainer._push_count
                        - payload["weight_version"]),
                })
                metrics.merge(payload["metrics"])
                self._fold_gauges(metrics)
                return
            yield payload

    def _get(self):
        t = self._thread
        while True:
            try:
                return self._q.get(timeout=0.5)
            except queue.Empty:
                if self._stop.is_set() or t is None or not t.is_alive():
                    raise PipelineClosed(
                        "rollout pipeline stopped mid-step") from None

    # -- off-hot-path control plane ----------------------------------------

    def submit_step_stats(self, **stats) -> None:
        """Foreground hands a finished step's stats over; the producer runs
        the manager scrape + balancer call before its next stream, and the
        resulting gauges land in the NEXT consumed step's record (gauges,
        so one step of lag is benign)."""
        self._stats_q.put(stats)

    def _drain_stats(self) -> None:
        trainer = self.trainer
        while True:
            try:
                stats = self._stats_q.get_nowait()
            except queue.Empty:
                return
            gauges: dict[str, float] = {}
            try:
                gauges.update(trainer.rollout.scrape_manager_metrics())
                resp = trainer.rollout.update_metrics(**stats)
                if resp.get("max_local_gen_s"):
                    # the balancer's next local-generation budget feeds the
                    # producer's own next generate_stream directly
                    trainer._max_local_gen_s = float(resp["max_local_gen_s"])
                    gauges["training/max_local_gen_s"] = \
                        trainer._max_local_gen_s
                    gauges["training/num_rollout_instances"] = float(
                        resp.get("num_instances", 0))
            except Exception:  # noqa: BLE001 — telemetry must not kill a lane
                log.exception("pipeline balancer round failed")
            if gauges:
                with self._gauges_lock:
                    self._gauges.update(gauges)

    def _fold_gauges(self, metrics: MetricsTracker) -> None:
        with self._gauges_lock:
            gauges, self._gauges = self._gauges, {}
        if gauges:
            metrics.update_gauge(gauges)
