"""Stream PPO/GRPO actor: per-ibatch fwd/bwd with gradient accumulation and
optimizer step at minibatch boundaries.

TPU-native equivalent of the reference's C8 ``StreamDataParallelPPOActor``
(``stream_dp_actor.py:58-231``): the input is already a sub-minibatch;
gradients accumulate across calls scaled by ``loss_scale_factor``; the
optimizer steps only when ``is_opt_step`` is set (reference :226-230, the
cumulative-minibatch-boundary logic lives in the trainer). Instead of
FSDP+NCCL, params/grads/opt-state shard over the (fsdp, tp) mesh axes and
GSPMD inserts the collectives.

Also provides ``compute_log_prob`` (the old/ref logprob pass, reference
stream_ray_trainer.py:425-439) and the ref-policy variant.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from polyrl_tpu.models import decoder
from polyrl_tpu.ops import core_algos


@dataclasses.dataclass(frozen=True)
class ActorConfig:
    policy_loss: str = "vanilla"          # vanilla | gpg | clip_cov
    clip_ratio: float = 0.2
    clip_ratio_low: float | None = None
    clip_ratio_high: float | None = None
    clip_ratio_c: float = 3.0
    entropy_coeff: float = 0.0
    use_kl_loss: bool = False             # GRPO-style in-loss KL
    kl_loss_coef: float = 0.001
    kl_loss_type: str = "low_var_kl"
    loss_agg_mode: str = "token-mean"
    lr: float = 1e-6
    lr_warmup_steps: int = 0
    weight_decay: float = 0.01
    max_grad_norm: float = 1.0
    # host-offload optimizer state between steps: frees HBM for the rollout
    # phase in colocated time-slicing (the reference's FSDP optimizer CPU
    # offload, stream_fsdp_workers.py:308-316,386-389)
    offload_optimizer: bool = False
    # LoRA fine-tuning (models/lora.py; the reference exposes this through
    # verl's config but marks it untested, stream_fsdp_workers.py:224):
    # rank > 0 wraps attention + dense-MLP weights in adapters, freezes the
    # base, and the optimizer updates only a/b. Weight pushes merge.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # Skip (don't apply) optimizer updates containing non-finite values: a
    # single poisoned minibatch (corrupt rollout data, overflowed loss) must
    # degrade one step, not NaN the params and cascade NaN logits into every
    # engine at the next weight sync. 0 disables the guard.
    max_nonfinite_skips: int = 100
    ppo_epochs: int = 1                   # reference guards ppo_epochs==1 (stream_dp_actor.py:145)
    remat: bool = True


def make_optimizer(cfg: ActorConfig, total_steps: int = 0) -> optax.GradientTransformation:
    """AdamW with grad clipping; warmup (+cosine decay when total_steps>0)."""
    if total_steps > 0:
        sched = optax.warmup_cosine_decay_schedule(
            0.0, cfg.lr, max(cfg.lr_warmup_steps, 1), total_steps
        )
    elif cfg.lr_warmup_steps > 0:
        sched = optax.linear_schedule(0.0, cfg.lr, cfg.lr_warmup_steps)
    else:
        sched = cfg.lr
    opt = optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adamw(sched, b1=0.9, b2=0.999, eps=1e-8, weight_decay=cfg.weight_decay),
    )
    if cfg.max_nonfinite_skips > 0:
        opt = optax.apply_if_finite(opt, max_consecutive_errors=cfg.max_nonfinite_skips)
    return opt


def default_train_attention():
    """Default training attention: Pallas flash on TPU (O(T) memory — the
    reference's flash-attn varlen role), dense masked attention elsewhere."""
    from polyrl_tpu.ops import flash

    return flash.auto_train_attention()


def _model_logprobs_entropy(params, model_cfg, input_ids, positions, attn_mask,
                            responses, response_mask, remat, compute_entropy,
                            attn_fn=None, layers_fn=None):
    """Forward over [B, T_total]; logprobs of response tokens [B, T_resp].
    ``attn_fn``: optional sequence-parallel attention (Ulysses/ring) for
    long-context training (SURVEY §5.7). ``layers_fn``: optional
    pipeline-parallel layer stack (parallel.pipeline)."""
    logits, _ = decoder.forward(params, model_cfg, input_ids, positions,
                                attn_mask, remat=remat, attn_fn=attn_fn,
                                layers_fn=layers_fn)
    t_resp = responses.shape[1]
    # logits at position i predict token i+1: responses occupy the last
    # t_resp positions of input_ids, so their predictors are shifted one left.
    pred_logits = logits[:, -t_resp - 1 : -1, :]
    # Finiteness contract: padded positions must come out 0, not NaN/-inf —
    # downstream the PPO ratio is exp(lp - old_lp) and `inf * mask(=0)` is
    # NaN, so masking at the consumer cannot recover. The where goes on the
    # LOGITS, before logsumexp/take_along_axis (double-where pattern): a
    # where on the logprob output alone zeroes the forward value but its
    # VJP still computes 0 * softmax(NaN) = NaN, poisoning the shared
    # weight gradients for the whole batch.
    pred_logits = jnp.where(response_mask[..., None] > 0, pred_logits, 0.0)
    logprobs = jnp.where(
        response_mask > 0,
        core_algos.logprobs_from_logits(pred_logits, responses), 0.0)
    if compute_entropy:
        entropy = jnp.where(response_mask > 0,
                            core_algos.entropy_from_logits(pred_logits), 0.0)
    else:
        entropy = None
    return logprobs, entropy


def bind_packed_attention(attn_fn, layers_fn, segment_ids):
    """Bind a packed batch's segment ids into the attention machinery —
    ONE place for the dispatch shared by the actor's logprob pass and the
    critic's value pass. Returns ``(attn, lf)`` for ``decoder.forward``:

    - ``layers_fn`` set (packed × pipeline): the stage attention takes the
      segment ids; an SP attn_fn alongside it is rejected here too (not
      just in build_trainer) because decoder.forward would silently ignore
      it — the pipeline computes its own stage attention.
    - ``attn_fn`` set (packed × SP): the segment-aware Ulysses/ring fn.
    - neither: the single-logical-device segment-id flash kernel.
    """
    from polyrl_tpu.ops import flash

    if layers_fn is not None:
        if attn_fn is not None:
            raise ValueError(
                "packed pass got BOTH an SP attn_fn and a pipeline "
                "layers_fn; the pipeline computes its own stage attention")
        return None, (lambda layers, x, cos, sin, am: layers_fn(
            layers, x, cos, sin, am, segment_ids=segment_ids))
    if attn_fn is None:
        return (lambda q, k, v, am: flash.flash_attention_train(
            q, k, v, am, causal=True, segment_ids=segment_ids)), None
    return (lambda q, k, v, am: attn_fn(q, k, v, am, segment_ids)), None


def _packed_logprobs_entropy(params, model_cfg, input_ids, positions,
                             attn_mask, segment_ids, remat, compute_entropy,
                             loss_mask=None, attn_fn=None, layers_fn=None):
    """Packed-row (remove-padding) variant: rows hold several trajectories
    separated by segment ids (reference use_remove_padding + flash varlen,
    stream_dp_actor.py:41-47). Returns per-COLUMN logprobs [R, L]: column t
    holds the logprob of input_ids[:, t] predicted from column t-1 — response
    tokens are selected by the caller's loss_mask (never at column 0, since a
    segment always starts with >= 1 prompt token).

    ``loss_mask`` (optional, [R, L]) enables the same double-where finiteness
    guard as the padded path: logits at columns outside the mask are zeroed
    BEFORE the logprob computation so a NaN there (pack-padding columns)
    cannot reach the forward value or the gradient.

    ``attn_fn`` (optional): a segment-aware SP attention
    (parallel.sequence.make_sp_attention(packed=True)) — signature
    (q, k, v, token_mask, segment_ids) — so packed training composes with
    sp > 1 (the reference's default long-context configuration,
    stream_dp_actor.py:37-47,135); defaults to the single-logical-device
    segment-id flash kernel."""
    attn, lf = bind_packed_attention(attn_fn, layers_fn, segment_ids)
    logits, _ = decoder.forward(params, model_cfg, input_ids, positions,
                                attn_mask, remat=remat, attn_fn=attn,
                                layers_fn=lf)
    pred = logits[:, :-1, :]
    targets = input_ids[:, 1:]
    if loss_mask is not None:
        pred = jnp.where(loss_mask[:, 1:, None] > 0, pred, 0.0)
    lp = core_algos.logprobs_from_logits(pred, targets)
    lp = jnp.pad(lp, ((0, 0), (1, 0)))
    if compute_entropy:
        ent = jnp.pad(core_algos.entropy_from_logits(pred), ((0, 0), (1, 0)))
    else:
        ent = None
    if loss_mask is not None:
        lp = jnp.where(loss_mask > 0, lp, 0.0)
        if ent is not None:
            ent = jnp.where(loss_mask > 0, ent, 0.0)
    return lp, ent


class StreamActor:
    """Owns params + optimizer + accumulated grads; stream-update semantics."""

    def __init__(
        self,
        model_cfg: decoder.ModelConfig,
        cfg: ActorConfig,
        params: Any,
        mesh=None,
        attn_fn=None,
        layers_fn=None,
        packed_attn_fn=None,
    ):
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.mesh = mesh
        self.attn_fn = attn_fn if attn_fn is not None else default_train_attention()
        self.layers_fn = layers_fn  # pipeline-parallel layer stack (pp > 1)
        # segment-aware SP attention for the packed (remove-padding) passes;
        # None → the single-logical-device segment-id flash kernel
        self.packed_attn_fn = packed_attn_fn
        self._lora = cfg.lora_rank > 0
        if self._lora:
            from polyrl_tpu.models import lora as lora_mod

            params = lora_mod.wrap_lora(
                params, jax.random.PRNGKey(7919 + cfg.lora_rank),
                cfg.lora_rank, cfg.lora_alpha)
        if mesh is not None:
            # GSPMD entry: params shard over (fsdp, tp) per decoder.param_specs
            # and every feed shards over the batch spec (see update_stream);
            # grads/opt state inherit the layout through jit propagation.
            # Works identically for single-host multi-chip and jax.distributed
            # multi-host (the mesh just spans more processes).
            from polyrl_tpu.parallel import mesh as meshlib

            specs = decoder.param_specs(model_cfg)
            if self._lora:
                from polyrl_tpu.models import lora as lora_mod

                specs = lora_mod.lora_param_specs(specs)
            params = meshlib.shard_params(mesh, params, specs)
        self.params = params
        self.optimizer = make_optimizer(cfg)
        if self._lora:
            # adapters are the ONLY trainable leaves: frozen leaves get
            # set_to_zero updates and no optimizer state
            from polyrl_tpu.models import lora as lora_mod

            self.optimizer = lora_mod.lora_optimizer(self.optimizer, params)
        self.opt_state = self.optimizer.init(params)
        if self._lora:
            from polyrl_tpu.models import lora as lora_mod

            self._labels = lora_mod.lora_labels(params)
        else:
            self._labels = None
        self.accum_grads = self._zero_accum(params)
        # sum of loss_scales accumulated since the last opt step: a tail
        # flush renormalizes by it so a partial minibatch sees the same
        # effective gradient scale as a full one (mean over actual micros,
        # not sum/G — reference loss_scale_factor semantics)
        self._accum_scale = 0.0
        self._update_fns: dict = {}
        self._logprob_fns: dict = {}
        self._opt_offloaded = False
        self._opt_shardings = None

    def export_params(self):
        """Params in the plain full-precision layout the rollout plane and
        transfer fabric expect: LoRA adapters merged into their bases; a
        plain tree passes through unchanged."""
        if not self._lora:
            return self.params
        from polyrl_tpu.models import lora as lora_mod

        if not hasattr(self, "_merge_fn"):
            self._merge_fn = jax.jit(lora_mod.merge_lora)
        return self._merge_fn(self.params)

    # -- optimizer host offload (reference FSDP opt CPU offload,
    # stream_fsdp_workers.py:308-316: load lazily, offload after step) ----

    def offload_opt_state(self) -> None:
        """Move optimizer state to host memory, freeing its HBM for the
        rollout phase. No-op unless cfg.offload_optimizer."""
        if not self.cfg.offload_optimizer or self._opt_offloaded:
            return
        self._opt_shardings = jax.tree_util.tree_map(
            lambda x: x.sharding if isinstance(x, jax.Array) else None,
            self.opt_state)
        self.opt_state = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if isinstance(x, jax.Array) else x,
            self.opt_state)
        self._opt_offloaded = True

    def load_opt_state(self) -> None:
        """Bring offloaded optimizer state back to the mesh."""
        if not self._opt_offloaded:
            return
        self.opt_state = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s) if s is not None else x,
            self.opt_state, self._opt_shardings)
        self._opt_offloaded = False

    # -- jitted kernels ---------------------------------------------------

    def _loss_fn(self, params, batch, loss_scale: float):
        cfg = self.cfg
        if "segment_ids" in batch:
            # packed rows: loss_mask plays response_mask; advantages /
            # old_log_probs already live in the packed [R, L] layout
            logprobs, entropy = _packed_logprobs_entropy(
                params, self.model_cfg,
                batch["input_ids"], batch["positions"],
                batch["attention_mask"], batch["segment_ids"],
                cfg.remat, cfg.entropy_coeff != 0.0,
                loss_mask=batch["loss_mask"], attn_fn=self.packed_attn_fn,
                layers_fn=self.layers_fn,
            )
            batch = dict(batch, response_mask=batch["loss_mask"])
        else:
            logprobs, entropy = _model_logprobs_entropy(
                params, self.model_cfg,
                batch["input_ids"], batch["positions"], batch["attention_mask"],
                batch["responses"], batch["response_mask"],
                cfg.remat, cfg.entropy_coeff != 0.0, attn_fn=self.attn_fn,
                layers_fn=self.layers_fn,
            )
        loss_fn = core_algos.get_policy_loss_fn(cfg.policy_loss)
        pg_loss, clipfrac, approx_kl, clipfrac_lower = loss_fn(
            batch["old_log_probs"], logprobs, batch["advantages"],
            batch["response_mask"],
            clip_ratio=cfg.clip_ratio, clip_ratio_low=cfg.clip_ratio_low,
            clip_ratio_high=cfg.clip_ratio_high, clip_ratio_c=cfg.clip_ratio_c,
            loss_agg_mode=cfg.loss_agg_mode,
        ) if cfg.policy_loss != "gpg" else loss_fn(
            batch["old_log_probs"], logprobs, batch["advantages"],
            batch["response_mask"], loss_agg_mode=cfg.loss_agg_mode,
        )
        loss = pg_loss
        metrics = {
            "actor/pg_loss": pg_loss,
            "actor/clipfrac": clipfrac,
            "actor/approx_kl": approx_kl,
            "actor/clipfrac_lower": clipfrac_lower,
        }
        if cfg.entropy_coeff != 0.0:
            ent = core_algos.agg_loss(entropy, batch["response_mask"], cfg.loss_agg_mode)
            loss = loss - cfg.entropy_coeff * ent
            metrics["actor/entropy"] = ent
        if cfg.use_kl_loss:
            kld = core_algos.kl_penalty(logprobs, batch["ref_log_probs"], cfg.kl_loss_type)
            kl_loss = core_algos.agg_loss(kld, batch["response_mask"], cfg.loss_agg_mode)
            loss = loss + cfg.kl_loss_coef * kl_loss
            metrics["actor/kl_loss"] = kl_loss
        return loss * loss_scale, metrics

    def _zero_accum(self, tree):
        """Gradient-accumulation buffers: full zeros_like normally; under
        LoRA the frozen leaves collapse to scalar placeholders — a second
        full model copy in HBM (plus full-size accumulate adds every
        micro) would give up most of LoRA's training-memory win."""
        if self._labels is None:
            return jax.tree_util.tree_map(jnp.zeros_like, tree)
        return jax.tree_util.tree_map(
            lambda x, l: (jnp.zeros((), x.dtype) if l == "freeze"
                          else jnp.zeros_like(x)), tree, self._labels)

    def _build_update(self, is_opt_step: bool):
        optimizer = self.optimizer
        labels = self._labels

        def update(params, opt_state, accum_grads, batch, loss_scale):
            (loss, metrics), grads = jax.value_and_grad(self._loss_fn, has_aux=True)(
                params, batch, loss_scale
            )
            if labels is None:
                accum_grads = jax.tree_util.tree_map(jnp.add, accum_grads,
                                                     grads)
            else:
                # frozen leaves keep their scalar placeholder (their grads
                # are structurally zero via mm's stop_gradient anyway)
                accum_grads = jax.tree_util.tree_map(
                    lambda a, g, l: a if l == "freeze" else a + g,
                    accum_grads, grads, labels)
            if is_opt_step:
                updates, opt_state = optimizer.update(accum_grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                metrics = dict(metrics)
                metrics["actor/grad_norm"] = optax.global_norm(accum_grads)
                if hasattr(opt_state, "total_notfinite"):
                    metrics["actor/nonfinite_skips"] = opt_state.total_notfinite
                accum_grads = jax.tree_util.tree_map(jnp.zeros_like, accum_grads)
            return params, opt_state, accum_grads, loss, metrics

        return jax.jit(update, donate_argnums=(0, 1, 2))

    def _shard_feed(self, batch: dict) -> dict:
        """Batch-shard a host-side feed over the mesh (no-op without one).
        Each process supplies the FULL array; device_put slices the local
        shards — the jax multi-host data path (per-host data sharding)."""
        if self.mesh is None:
            return batch
        from polyrl_tpu.parallel import mesh as meshlib

        return meshlib.shard_batch(self.mesh, batch)

    def update_stream(self, batch: dict, is_opt_step: bool, loss_scale: float = 1.0) -> dict:
        """One sub-minibatch fwd/bwd (+opt step at boundary). ``batch`` is a
        dict of arrays: input_ids, positions, attention_mask, responses,
        response_mask, advantages, old_log_probs [, ref_log_probs]."""
        batch = self._shard_feed(batch)
        self.load_opt_state()
        if is_opt_step not in self._update_fns:
            self._update_fns[is_opt_step] = self._build_update(is_opt_step)
        fn = self._update_fns[is_opt_step]
        self.params, self.opt_state, self.accum_grads, loss, metrics = fn(
            self.params, self.opt_state, self.accum_grads, batch,
            jnp.asarray(loss_scale, jnp.float32),
        )
        self._accum_scale = 0.0 if is_opt_step else self._accum_scale + loss_scale
        return metrics

    def flush_opt_step(self) -> dict:
        """Apply accumulated grads without new data — the stream trainer's
        final flush when a short batch (dropped groups) ends mid-minibatch.
        Accumulated grads are renormalized by the summed loss_scale so the
        partial minibatch's update has the same effective gradient scale
        (mean over its micros) as a full minibatch, not a sum/G fraction."""
        self.load_opt_state()
        if not hasattr(self, "_flush_fn"):
            optimizer = self.optimizer

            def flush(params, opt_state, accum_grads, inv_scale):
                accum_grads = jax.tree_util.tree_map(
                    lambda g: g * inv_scale, accum_grads)
                updates, opt_state = optimizer.update(accum_grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                gn = optax.global_norm(accum_grads)
                accum_grads = jax.tree_util.tree_map(jnp.zeros_like, accum_grads)
                return params, opt_state, accum_grads, gn

            self._flush_fn = jax.jit(flush, donate_argnums=(0, 1, 2))
        inv = 1.0 / self._accum_scale if self._accum_scale > 0 else 1.0
        self.params, self.opt_state, self.accum_grads, gn = self._flush_fn(
            self.params, self.opt_state, self.accum_grads,
            jnp.asarray(inv, jnp.float32))
        self._accum_scale = 0.0
        return {"actor/grad_norm": gn}

    def compute_log_prob(self, batch: dict, compute_entropy: bool = True):
        """Old-logprob pass (no grad). Returns (logprobs, entropy|None)."""
        batch = self._shard_feed(batch)
        if compute_entropy not in self._logprob_fns:
            self._logprob_fns[compute_entropy] = jax.jit(
                partial(_model_logprobs_entropy, remat=False,
                        compute_entropy=compute_entropy,
                        attn_fn=self.attn_fn, layers_fn=self.layers_fn),
                static_argnums=(1,),
            )
        return self._logprob_fns[compute_entropy](
            self.params, self.model_cfg,
            batch["input_ids"], batch["positions"], batch["attention_mask"],
            batch["responses"], batch["response_mask"],
        )

    def compute_log_prob_packed(self, batch: dict, compute_entropy: bool = True,
                                params=None):
        """Packed-row logprob pass: [R, L] per-column logprobs aligned so
        loss_mask selects response tokens (see _packed_logprobs_entropy)."""
        batch = self._shard_feed(batch)
        key = ("packed", compute_entropy)
        if key not in self._logprob_fns:
            self._logprob_fns[key] = jax.jit(
                partial(_packed_logprobs_entropy, remat=False,
                        compute_entropy=compute_entropy,
                        attn_fn=self.packed_attn_fn,
                        layers_fn=self.layers_fn),
                static_argnums=(1,),
            )
        return self._logprob_fns[key](
            params if params is not None else self.params, self.model_cfg,
            batch["input_ids"], batch["positions"], batch["attention_mask"],
            batch["segment_ids"], loss_mask=batch.get("loss_mask"),
        )


class ReferencePolicy:
    """Frozen reference policy for KL (reference ref worker role).

    Owns a COPY of the params: the actor's update step donates its param
    buffers to XLA, so sharing the initial pytree would leave this policy
    holding deleted buffers after the first optimizer step.
    """

    def __init__(self, model_cfg: decoder.ModelConfig, params: Any, attn_fn=None):
        self.model_cfg = model_cfg
        self.params = jax.tree_util.tree_map(jnp.copy, params)
        if attn_fn is None:
            attn_fn = default_train_attention()
        self._fn = jax.jit(
            partial(_model_logprobs_entropy, remat=False, compute_entropy=False,
                    attn_fn=attn_fn),
            static_argnums=(1,),
        )
        self._packed_fn = jax.jit(
            partial(_packed_logprobs_entropy, remat=False,
                    compute_entropy=False),
            static_argnums=(1,),
        )

    def compute_log_prob(self, batch: dict):
        lp, _ = self._fn(
            self.params, self.model_cfg,
            batch["input_ids"], batch["positions"], batch["attention_mask"],
            batch["responses"], batch["response_mask"],
        )
        return lp

    def compute_log_prob_packed(self, batch: dict):
        lp, _ = self._packed_fn(
            self.params, self.model_cfg,
            batch["input_ids"], batch["positions"], batch["attention_mask"],
            batch["segment_ids"], loss_mask=batch.get("loss_mask"),
        )
        return lp
