"""Weight-transfer sender/receiver agents.

TPU-native redesign of the reference's fabric (sender:
rlboost/weight_transfer/sender_agent.py:163-693, receiver:
receiver_agent.py:55-308). The reference bootstraps over RPyC and signals
status over ZMQ; here both collapse into ONE newline-delimited-JSON TCP
control channel (SURVEY §5.8 recommends collapsing the protocol diversity).

Flow (mirrors §3.3 of the survey):
- Receiver (inside each rollout server) allocates its buffer from the model
  layout, starts N TCP listener streams, connects to its assigned sender's
  control port and registers {instance, buffer_len, stream host/ports}.
- Sender holds the packed flat weight buffer. Its event loop bumps the
  version on trainer signal AND polls the manager every ``poll_s`` seconds
  (pull model — enables late joiners, sender_agent.py:324-340):
  /get_receive_instances -> stale instances -> parallel TCP fan-out ->
  per-instance "transfer_done" on the control channel -> async
  POST /update_weights so each instance rejoins the pool ASAP
  (sender_agent.py:617-624).
"""

from __future__ import annotations

import contextlib
import json
import logging
import queue
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from polyrl_tpu import obs

from .layout import ParamLayout, alloc_buffer
from .tcp_engine import ReceiverSockets, TcpTransferEngine

log = logging.getLogger(__name__)


def _send_json(sock: socket.socket, obj: dict) -> None:
    sock.sendall((json.dumps(obj) + "\n").encode())


class _LineReader:
    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""

    def read(self, timeout: float | None = None) -> dict | None:
        self._sock.settimeout(timeout)
        while b"\n" not in self._buf:
            try:
                chunk = self._sock.recv(65536)
            except socket.timeout:
                return None
            if not chunk:
                raise ConnectionError("control channel closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return json.loads(line)


# --------------------------------------------------------------------------
# Receiver
# --------------------------------------------------------------------------


class ReceiverAgent:
    """Runs inside a rollout server; lands weight bytes into a host buffer.

    Unlike the reference (mp.Process per TP-rank-0, receiver_agent.py:295),
    this runs as a thread: ``recv_into`` releases the GIL, and the JAX server
    is a single process per host — the buffer is handed to the engine via
    ``unpack_params`` + ``device_put`` (the TPU analogue of the reference's
    chunked host->GPU broadcast, patches.py:169-241).
    """

    def __init__(self, layout: ParamLayout, instance_endpoint: str,
                 sender_endpoint: str, num_streams: int = 4,
                 listen_host: str = "0.0.0.0", advertise_host: str | None = None):
        self.layout = layout
        self.buffer = alloc_buffer(layout)
        self.instance_endpoint = instance_endpoint
        self.sender_host, self.sender_port = _split(sender_endpoint)
        self.sockets = ReceiverSockets(self.buffer, num_streams, listen_host)
        self.advertise_host = advertise_host or "127.0.0.1"
        self.version = -1
        self.error: str | None = None
        self._armed_version = -1  # version of the round currently landing
        # held around every on_tensor emission batch (and the completion
        # tail): the prepare handler takes it before arming the NEXT round,
        # so a new push can never overwrite buffer bytes an installer is
        # still reading (torn-tensor guard for back-to-back syncs)
        self._install_lock = threading.Lock()
        self._version_cv = threading.Condition()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        backoff = 0.2
        while not self._stop.is_set():
            try:
                with socket.create_connection(
                        (self.sender_host, self.sender_port), timeout=30.0) as s:
                    backoff = 0.2
                    _send_json(s, {
                        "cmd": "register",
                        "instance": self.instance_endpoint,
                        "buffer_len": int(self.buffer.nbytes),
                        "host": self.advertise_host,
                        "ports": self.sockets.ports,
                    })
                    reader = _LineReader(s)
                    while not self._stop.is_set():
                        msg = reader.read(timeout=1.0)
                        if msg is None:
                            continue
                        if msg.get("event") == "prepare":
                            # serialize behind a mid-flight incremental
                            # install: its buffer reads must finish before
                            # this round's bytes land over them (sender
                            # retries if "ready" is delayed past its gate)
                            with self._install_lock:
                                with self._version_cv:
                                    self._armed_version = int(
                                        msg.get("version", -1))
                                self.sockets.arm(int(msg["round"]))
                            _send_json(s, {"event": "ready",
                                           "instance": self.instance_endpoint})
                        elif msg.get("event") == "transfer_done":
                            if msg.get("status") != "success":
                                log.error("transfer failed: %s", msg)
                                continue
                            self.sockets.wait(timeout=600.0)
                            with self._version_cv:
                                self.version = int(msg["version"])
                                self._version_cv.notify_all()
                        elif msg.get("event") == "error":
                            # permanent rejection (e.g. layout/buffer-size
                            # mismatch): surface loudly, stop retrying
                            self.error = str(msg.get("error", "unknown"))
                            log.error("sender rejected registration: %s",
                                      self.error)
                            return
            except (OSError, ConnectionError) as exc:
                if self._stop.is_set():
                    return
                log.warning("receiver control reconnect (%s)", exc)
                time.sleep(backoff)
                backoff = min(backoff * 2, 5.0)

    def wait_for_version(self, version: int, timeout: float = 600.0,
                         on_tensor=None) -> int:
        """Block until weights of at least ``version`` are in the buffer
        (the reference's 'receive_weights' wait, receiver_agent.py:257-268).
        Returns the version whose bytes were actually installed — ≥ the
        requested one when a superseding round landed instead (callers
        recording ``engine.weight_version`` must use the RETURN value, not
        the request, or they under-report until the next push).

        ``on_tensor(entry, np_view)``: incremental install hook — invoked
        IN LAYOUT ORDER for each tensor whose bytes have fully landed,
        while later tensors are still on the wire (overlaps the wire with
        the device upload; reference overlap: sender_agent.py:567-647).
        Landed bytes are final (streams send monotonically from a stable
        snapshot), so a completed tensor never changes within a round. If
        a retry/newer round supersedes the one being tailed, every tensor
        is re-emitted from the final buffer — the consumer must treat
        emissions as idempotent upserts by name.

        The install lock is dropped BETWEEN tensor emissions (advisor r4:
        ``on_tensor`` is a device_put that can take seconds, and the
        sender's prepare→ready gate is 60 s — holding the lock across a
        whole emission batch starved back-to-back pushes into spurious
        manager aborts). A prepare arriving between two tensors arms the
        new round; the next iteration observes it under the lock and stops
        reading the old bytes before any stream can overwrite them."""
        deadline = time.monotonic() + timeout
        emitted = 0
        tail_round = None
        from .layout import covered_entries

        def emit_landed() -> None:
            nonlocal emitted, tail_round
            if on_tensor is None:
                return
            while True:
                with self._version_cv:
                    armed = self._armed_version
                if armed != target:  # only tail the round we wait on
                    return
                with self._install_lock:
                    rnd = self.sockets._round
                    if rnd != tail_round:
                        tail_round, emitted = rnd, 0  # retry: start over
                    es = covered_entries(self.layout,
                                         self.sockets.coverage(), emitted,
                                         limit=1)
                    if not es:
                        return
                    e = es[0]  # ONE tensor per lock hold (see docstring)
                    on_tensor(e, self.buffer[e.offset : e.offset + e.nbytes])
                    emitted += 1

        target = version
        while True:
            with self._version_cv:
                while self.version < target:
                    if self._stop.is_set():
                        raise ConnectionError("receiver stopped")
                    if self.error is not None:
                        raise ConnectionError(
                            f"receiver registration rejected: {self.error}")
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise TimeoutError(
                            f"weights v{target} not received "
                            f"(have v{self.version})")
                    if on_tensor is not None:
                        self._version_cv.release()
                        try:
                            emit_landed()
                        finally:
                            self._version_cv.acquire()
                        self._version_cv.wait(min(left, 0.05))
                    else:
                        self._version_cv.wait(min(left, 1.0))
                final = self.version
            if on_tensor is None:
                return final
            # completion tail: emit the remaining entries, one lock hold
            # per tensor (the NEXT round's prepare waits out at most one
            # device_put, not the whole tail). The round id AND armed
            # version are re-read under the lock on EVERY iteration, and
            # emission is gated on the current round's landed coverage: a
            # SAME-version re-push (sender retry) arming mid-tail changes
            # sockets._round and resets coverage, which restarts the tail
            # and blocks it until the new round's bytes land — without
            # this the tail would keep emitting buffer ranges the retry's
            # streams are actively overwriting (advisor r5; the old code
            # only checked the round once and leaned on the implicit
            # byte-identical-same-version invariant).
            superseded = False
            if final != target:
                emitted, tail_round = 0, None  # stale pre-wait progress
            while not superseded:
                progressed = False
                with self._install_lock:
                    with self._version_cv:
                        armed = self._armed_version
                        cur = self.version
                    if armed > cur or cur != final:
                        # a SUPERSEDING round armed (streams will land over
                        # the buffer) — or armed AND completed within one
                        # inter-tensor lock gap (cur moved past the version
                        # this tail was emitting): either way the remaining
                        # bytes are not round-``final``'s — restart the
                        # tail against the newest version (still "at least
                        # version"). Without the ``cur != final`` arm a
                        # fully-landed supersede would mix two versions'
                        # tensors into one install.
                        target = max(armed, cur)
                        emitted, tail_round = 0, None
                        superseded = True
                        continue
                    rnd = self.sockets._round
                    if rnd != tail_round:
                        # re-push of the SAME version restarted the round:
                        # start over against its (reset) coverage
                        tail_round, emitted = rnd, 0
                    if emitted >= len(self.layout.entries):
                        return final
                    es = covered_entries(self.layout,
                                         self.sockets.coverage(), emitted,
                                         limit=1)
                    if es:
                        e = es[0]
                        on_tensor(e,
                                  self.buffer[e.offset : e.offset + e.nbytes])
                        emitted += 1
                        progressed = True
                if not progressed:
                    # mid re-push: the next entry's bytes have not landed
                    # yet — wait for stream progress instead of emitting
                    # bytes that are being overwritten
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"weights v{final} install tail stalled behind "
                            f"an incomplete re-push")
                    with self._version_cv:
                        self._version_cv.wait(0.05)

    def stop(self) -> None:
        self._stop.set()
        with self._version_cv:
            self._version_cv.notify_all()  # break waiting installers out
        self.sockets.close()
        if self._thread:
            self._thread.join(timeout=5.0)


# --------------------------------------------------------------------------
# Sender
# --------------------------------------------------------------------------


@dataclass
class _Registration:
    instance: str
    host: str
    ports: list[int]
    sock: socket.socket
    lock: threading.Lock = field(default_factory=threading.Lock)
    ready: threading.Event = field(default_factory=threading.Event)
    pushed_version: int = -1


class SenderAgent:
    """Trainer-side transfer agent (thread; reference uses an mp.Process,
    sender_agent.py:682-694 — a thread suffices since pack/send release the
    GIL and lets the trainer overlap transfer with the next step)."""

    def __init__(self, buffer: np.ndarray, manager_client=None,
                 listen_host: str = "0.0.0.0", num_streams: int = 4,
                 poll_s: float = 1.0, advertise_host: str | None = None,
                 bind_host: str | None = None):
        self.buffer = buffer
        self.manager = manager_client
        # bind_host pins this sender's outbound data streams to one NIC
        # (SenderGroup runs one agent per interface for aggregate bandwidth)
        self.engine = TcpTransferEngine(num_streams=num_streams,
                                        bind_host=bind_host)
        self._notify_pool = ThreadPoolExecutor(max_workers=4)
        self.poll_s = poll_s
        self.reg_wait_s = 10.0
        self.version = -1
        self._regs: dict[str, _Registration] = {}
        self._regs_lock = threading.Lock()
        self._cmds: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        # (buffer, version) pairing protocol: a push round snapshots both
        # under _cv with _inflight+=1; a swap/pack waits for _inflight==0.
        # Packing into a DIFFERENT (back) buffer overlaps with in-flight
        # rounds — only the pointer swap synchronizes (the reference gets
        # this overlap from its agent process, sender_agent.py:682-694).
        self._cv = threading.Condition()
        self._inflight = 0
        self._packing = False
        self._watermark = None  # streaming push: gates sends behind the pack
        self._poisoned_version = -1  # streamed pack died: never push this
        # serial rounds start the clock after the pack; a streamed round's
        # wire trails the pack, so it gets the combined budget
        self.push_timeout_s = 600.0
        self.stream_push_timeout_s = 3600.0
        self._round_counter = 0  # unique per push attempt (stale-stream guard)
        # elastic-pool telemetry: full pushes to instances this sender had
        # never pushed before — the scale-up catch-up path (a late joiner
        # registers, the idle poll finds it stale, it gets the CURRENT
        # version in one round, then rides the normal push fan-out)
        self.catchup_pushes = 0
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((listen_host, 0))
        self._server.listen(64)
        self.control_port = self._server.getsockname()[1]
        self.endpoint = f"{advertise_host or _advertise_ip()}:{self.control_port}"
        self._threads: list[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        for target in (self._accept_loop, self._event_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass
        self.engine.shutdown()
        self._notify_pool.shutdown(wait=False, cancel_futures=True)
        for t in self._threads:
            t.join(timeout=5.0)

    # -- trainer API --------------------------------------------------------

    def signal_update(self, version: int | None = None) -> int:
        """Trainer signals new weights are packed (in-place into
        ``self.buffer``); returns the new version."""
        with self._cv:
            while self._inflight > 0:
                self._cv.wait()
            self.version = version if version is not None else self.version + 1
            self._watermark = None
            v = self.version
        self._cmds.put("update_weights")
        return v

    def signal_update_streaming(self, watermark,
                                version: int | None = None) -> int:
        """Streaming push: announce the version BEFORE packing; sends are
        gated behind ``watermark`` while the caller packs in place into
        ``self.buffer`` (the watermark orders buffer access: senders read
        only below it, the packer writes only above it). The reference's
        in-round sender pipeline (sender_agent.py:567-647)."""
        with self._cv:
            while self._inflight > 0:
                self._cv.wait()
            self.version = version if version is not None else self.version + 1
            self._watermark = watermark
            v = self.version
        self._cmds.put("update_weights")
        return v

    def mark_push_failed(self, version: int) -> None:
        """A streamed pack died mid-round: the buffer holds garbage for
        ``version``. Poison it so the poll loop stops re-pushing it every
        ``poll_s`` (each retry would fail at the watermark and spam the
        manager with aborts); the next successful signal/swap resumes."""
        with self._cv:
            self._poisoned_version = version
        log.error("weight push v%d poisoned (pack failed); waiting for a "
                  "new update", version)

    def swap_buffer(self, new_buffer: np.ndarray, version: int) -> np.ndarray:
        """Atomically install a freshly packed buffer; returns the old one
        (double-buffering: the caller packs the next update into it)."""
        with self._cv:
            while self._inflight > 0:
                self._cv.wait()
            old, self.buffer = self.buffer, new_buffer
            self.version = version
            self._watermark = None
        self._cmds.put("update_weights")
        return old

    class _PackGuard:
        def __init__(self, sender: "SenderAgent"):
            self._s = sender

        def __enter__(self):
            with self._s._cv:
                while self._s._inflight > 0 or self._s._packing:
                    self._s._cv.wait()
                self._s._packing = True

        def __exit__(self, *exc):
            with self._s._cv:
                self._s._packing = False
                self._s._cv.notify_all()

    def buffer_write_lock(self) -> "_PackGuard":
        """Guard for packing in place into ``self.buffer`` (direct mode);
        blocks while a push round is in flight and vice versa."""
        return SenderAgent._PackGuard(self)

    # -- registration server ------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        reader = _LineReader(conn)
        reg: _Registration | None = None
        try:
            while not self._stop.is_set():
                msg = reader.read(timeout=1.0)
                if msg is None:
                    continue
                if msg.get("cmd") == "register":
                    if int(msg["buffer_len"]) != int(self.buffer.nbytes):
                        _send_json(conn, {"event": "error",
                                          "error": "buffer size mismatch"})
                        return
                    reg = _Registration(instance=msg["instance"],
                                        host=msg["host"],
                                        ports=list(msg["ports"]), sock=conn)
                    with self._regs_lock:
                        self._regs[reg.instance] = reg
                    _send_json(conn, {"event": "registered",
                                      "version": self.version})
                    log.info("receiver registered: %s", reg.instance)
                elif msg.get("event") == "ready" and reg is not None:
                    reg.ready.set()
        except (ConnectionError, OSError):
            pass
        finally:
            if reg is not None:
                with self._regs_lock:
                    if self._regs.get(reg.instance) is reg:
                        del self._regs[reg.instance]

    # -- event loop (pull model) --------------------------------------------

    def _event_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._cmds.get(timeout=self.poll_s)
            except queue.Empty:
                pass  # idle poll — late joiners (sender_agent.py:324-340)
            if self._stop.is_set():
                return
            if self.version < 0:
                continue
            try:
                self._check_and_update_receivers()
            except Exception:  # noqa: BLE001 — keep the loop alive
                log.exception("weight push round failed")

    def _stale_instances(self, version: int) -> list[str]:
        if self.manager is None:
            with self._regs_lock:
                return [i for i, r in self._regs.items()
                        if r.pushed_version < version]
        resp = self.manager.get_receive_instances(self.endpoint)
        return [i["endpoint"] if isinstance(i, dict) else i
                for i in resp.get("instances", [])]

    def _wait_registration(self, instance: str) -> _Registration | None:
        """Bootstrap race: the manager may hand us an instance whose receiver
        hasn't connected yet (the reference's wait_for_receiver_registration,
        sender_agent.py:342-351)."""
        deadline = time.monotonic() + self.reg_wait_s
        while time.monotonic() < deadline and not self._stop.is_set():
            with self._regs_lock:
                reg = self._regs.get(instance)
            if reg is not None:
                return reg
            time.sleep(0.05)
        return None

    def _check_and_update_receivers(self) -> None:
        # snapshot (buffer, version) atomically; the round holds an inflight
        # ref so swaps/packs wait, but packing the BACK buffer proceeds in
        # parallel with the sends.
        with self._cv:
            while self._packing:
                self._cv.wait()
            version = self.version
            buffer = self.buffer
            watermark = self._watermark
            if version == self._poisoned_version:
                return  # failed streamed pack: nothing valid to push
            self._inflight += 1
        try:
            stale = self._stale_instances(version)
            if not stale:
                return
            threads = [threading.Thread(
                           target=self._push_instance,
                           args=(i, version, buffer, watermark), daemon=True)
                       for i in stale]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()

    def _abort_on_manager(self, instance: str) -> None:
        """Clear the manager's updating_weight CAS so the instance is
        retried next poll instead of being drained forever."""
        if self.manager is not None:
            try:
                self._notify_pool.submit(self.manager.abort_weight_update,
                                         [instance])
            except RuntimeError:
                # agent closing: notify pool already shut down; the manager
                # side times the CAS out on its own
                pass

    def _push_instance(self, instance: str, version: int,
                       buffer: np.ndarray, watermark=None) -> None:
        reg = self._wait_registration(instance)
        if reg is None:
            log.error("no receiver registration for %s; skipping push", instance)
            self._abort_on_manager(instance)
            return
        self._push_one(reg, version, buffer, watermark)

    def _push_one(self, reg: _Registration, version: int,
                  buffer: np.ndarray, watermark=None) -> None:
        with self._cv:
            self._round_counter += 1
            round_id = self._round_counter
        try:
            with reg.lock:
                reg.ready.clear()
                _send_json(reg.sock, {"event": "prepare", "version": version,
                                      "round": round_id})
                if not reg.ready.wait(timeout=60.0):
                    raise TimeoutError("receiver did not arm listeners")
                t0 = time.monotonic()
                batch = self.engine.transfer_submit_write(
                    reg.host, reg.ports, buffer, round_id=round_id,
                    watermark=watermark)
                batch.result(timeout=self.push_timeout_s if watermark is None
                             else self.stream_push_timeout_s)
                dt = time.monotonic() - t0
                _send_json(reg.sock, {"event": "transfer_done",
                                      "status": "success", "version": version})
            if reg.pushed_version < 0:
                self.catchup_pushes += 1
            reg.pushed_version = version
            mbps = buffer.nbytes / max(dt, 1e-9) / 1e6
            # per-instance push duration distribution: one slow receiver
            # (bad NIC, busy engine) shows up as a p99/max outlier that the
            # fleet-wide MB/s mean would average away
            obs.observe("transfer/push_s", dt)
            log.info("pushed v%d to %s: %.0f MB/s", version, reg.instance, mbps)
            if self.manager is not None:
                # async notify so the instance rejoins the pool without the
                # trainer's next pack blocking on the engine's weight load
                # (sender_agent.py:617-624)
                self._notify_pool.submit(
                    self.manager.update_weights, [reg.instance], version)
        except Exception as exc:  # noqa: BLE001
            log.error("push to %s failed: %s", reg.instance, exc)
            self._abort_on_manager(reg.instance)
            try:
                _send_json(reg.sock, {"event": "transfer_done",
                                      "status": "failure", "version": version,
                                      "error": str(exc)})
            except OSError:
                pass


class SenderGroup:
    """N sender agents, one per local NIC, sharing one packed buffer.

    The reference fans each trainer's weight push over
    ``num_mooncake_groups_per_sender`` engine groups bound to different
    node IPs (config.toml:19-20, fsdp_interface.py:97-138) so an 8B push
    saturates aggregate NIC bandwidth, not one interface. Here each group
    is a full :class:`SenderAgent` (own control endpoint + TCP engine
    source-bound to its NIC); the MANAGER partitions rollout instances
    across the groups when all endpoints are registered via
    ``PUT /update_weight_senders`` — per-group work is 1/N of the pool.

    The buffer is shared read-only during pushes; trainer-side mutation
    (``signal_update`` / ``swap_buffer`` / ``buffer_write_lock``) fans out
    to every agent so each agent's (buffer, version) snapshot invariant is
    preserved independently.
    """

    def __init__(self, buffer: np.ndarray, sender_ips: list[str],
                 manager_client=None, num_streams: int = 4,
                 poll_s: float = 1.0, listen_host: str = "0.0.0.0"):
        if not sender_ips:
            raise ValueError("SenderGroup needs at least one sender IP")
        self.manager = manager_client
        self.senders = [
            SenderAgent(buffer, manager_client=manager_client,
                        listen_host=listen_host, num_streams=num_streams,
                        poll_s=poll_s, advertise_host=ip, bind_host=ip)
            for ip in sender_ips
        ]

    @property
    def endpoints(self) -> list[str]:
        return [s.endpoint for s in self.senders]

    @property
    def version(self) -> int:
        return self.senders[0].version

    @property
    def buffer(self) -> np.ndarray:
        return self.senders[0].buffer

    def mark_push_failed(self, version: int) -> None:
        for s in self.senders:
            s.mark_push_failed(version)

    def start(self) -> None:
        for s in self.senders:
            s.start()

    def stop(self) -> None:
        for s in self.senders:
            s.stop()

    def signal_update(self, version: int | None = None) -> int:
        v = self.senders[0].signal_update(version)
        for s in self.senders[1:]:
            s.signal_update(v)
        return v

    def swap_buffer(self, new_buffer: np.ndarray, version: int) -> np.ndarray:
        old = self.senders[0].swap_buffer(new_buffer, version)
        for s in self.senders[1:]:
            s.swap_buffer(new_buffer, version)
        return old

    @contextlib.contextmanager
    def buffer_write_lock(self):
        """All-agents pack guard (no push round may be in flight on ANY
        NIC while the shared buffer is rewritten in place)."""
        with contextlib.ExitStack() as stack:
            for s in self.senders:
                stack.enter_context(s.buffer_write_lock())
            yield


def _split(endpoint: str) -> tuple[str, int]:
    host, port = endpoint.rsplit(":", 1)
    return host, int(port)


def _advertise_ip() -> str:
    from .nic import default_route_ip

    return default_route_ip()
