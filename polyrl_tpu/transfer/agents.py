"""Weight-transfer sender/receiver agents.

TPU-native redesign of the reference's fabric (sender:
rlboost/weight_transfer/sender_agent.py:163-693, receiver:
receiver_agent.py:55-308). The reference bootstraps over RPyC and signals
status over ZMQ; here both collapse into ONE newline-delimited-JSON TCP
control channel (SURVEY §5.8 recommends collapsing the protocol diversity).

Flow (mirrors §3.3 of the survey):
- Receiver (inside each rollout server) allocates its buffer from the model
  layout, starts N TCP listener streams, connects to its assigned sender's
  control port and registers {instance, buffer_len, stream host/ports}.
- Sender holds the packed flat weight buffer. Its event loop bumps the
  version on trainer signal AND polls the manager every ``poll_s`` seconds
  (pull model — enables late joiners, sender_agent.py:324-340):
  /get_receive_instances -> stale instances -> parallel TCP fan-out ->
  per-instance verify handshake on the control channel -> async
  POST /update_weights so each instance rejoins the pool ASAP
  (sender_agent.py:617-624).

Every push is **verified, resumable, and supervised** (ARCHITECTURE.md
"Weight-fabric fault tolerance"): after the wire, the sender ships the
round's frame manifest (per-range CRC32 digests) on the control channel;
the receiver checks coverage + digests against its landed buffer and only
a verified round installs the version. A ``verify_failed`` answer carries
the failed ranges, and the retry re-pushes ONLY those (the receiver's
coverage ledger survives into the resume round). Each attempt runs under a
bandwidth-keyed deadline (``bytes / min_bandwidth_mbps + slack`` instead
of the old flat 600 s / 3600 s), retries ride a jittered exponential
backoff up to ``retry_budget``, and budget exhaustion escalates the
instance to the laggard callback (``PoolManager.escalate_laggard`` drains
+ deregisters it — dead capacity stops being re-pushed every poll).
"""

from __future__ import annotations

import contextlib
import json
import logging
import queue
import random
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from polyrl_tpu import obs
from polyrl_tpu.rollout.faults import TransferFaultConfig

from .layout import ParamLayout, ShardSpec, alloc_buffer, build_resharding_map
from .tcp_engine import ReceiverSockets, TcpTransferEngine

log = logging.getLogger(__name__)


@dataclass
class TransferConfig:
    """``transfer.*`` config: supervision knobs for the weight-push fabric
    (README "Weight-fabric fault tolerance" knob blurb). The previously
    hardcoded flat timeouts (600 s serial / 3600 s streamed) survive only
    as CAPS — the operative per-attempt deadline is bandwidth-keyed."""
    # minimum acceptable effective push bandwidth, MB/s: an attempt's
    # deadline is bytes / (min_bandwidth_mbps * 1e6) + slack, capped below
    min_bandwidth_mbps: float = 50.0
    # deadline slack: fixed per-attempt overhead allowance (connection
    # setup, receiver arming, verify hand-off). Streamed rounds gate the
    # wire behind the in-place pack, so they get the larger slack.
    deadline_slack_s: float = 30.0
    stream_slack_s: float = 120.0
    # hard caps on any single attempt (the old flat timeouts)
    push_timeout_s: float = 600.0
    stream_push_timeout_s: float = 3600.0
    # prepare -> ready control handshake budget
    prepare_timeout_s: float = 60.0
    # integrity: CRC32 frame trailers are always on the wire; verify=False
    # skips the manifest handshake and installs on bare completion (the
    # pre-verification trusting path, kept as an escape hatch)
    verify: bool = True
    # per-push-call retry budget (attempts = retry_budget + 1) and the
    # jittered exponential backoff between attempts
    retry_budget: int = 2
    backoff_base_s: float = 0.5
    backoff_max_s: float = 10.0
    # transfer-plane chaos (rollout/faults.py TransferFaultInjector)
    fault_injection: TransferFaultConfig = field(
        default_factory=TransferFaultConfig)

    def push_deadline_s(self, nbytes: int, streamed: bool) -> float:
        cap = self.stream_push_timeout_s if streamed else self.push_timeout_s
        slack = self.stream_slack_s if streamed else self.deadline_slack_s
        bw = max(self.min_bandwidth_mbps, 1e-6) * 1e6
        return min(cap, nbytes / bw + slack)

    def stream_deadline_s(self, nbytes: int, streamed: bool) -> float:
        """Per-STREAM deadline of the sharded push: keyed to the bytes that
        one stream carries, so a stalled stream is detected after its own
        share's wire time — not after the whole round's — while the other
        streams keep landing."""
        return self.push_deadline_s(nbytes, streamed)


def _send_json(sock: socket.socket, obj: dict) -> None:
    sock.sendall((json.dumps(obj) + "\n").encode())


def _merge_ranges(rs: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Sorted union of (offset, length) ranges, merging overlaps/adjacency
    — a resume list must be disjoint (overlapping clears are idempotent on
    the receiver but would double-send bytes on the wire)."""
    rs = sorted((int(o), int(ln)) for o, ln in rs if int(ln) > 0)
    out: list[tuple[int, int]] = []
    for o, ln in rs:
        if out and o <= out[-1][0] + out[-1][1]:
            end = max(out[-1][0] + out[-1][1], o + ln)
            out[-1] = (out[-1][0], end - out[-1][0])
        else:
            out.append((o, ln))
    return out


class _LineReader:
    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""

    def read(self, timeout: float | None = None) -> dict | None:
        self._sock.settimeout(timeout)
        while b"\n" not in self._buf:
            try:
                chunk = self._sock.recv(65536)
            except socket.timeout:
                return None
            if not chunk:
                raise ConnectionError("control channel closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return json.loads(line)


# --------------------------------------------------------------------------
# Receiver
# --------------------------------------------------------------------------


class ReceiverAgent:
    """Runs inside a rollout server; lands weight bytes into a host buffer.

    Unlike the reference (mp.Process per TP-rank-0, receiver_agent.py:295),
    this runs as a thread: ``recv_into`` releases the GIL, and the JAX server
    is a single process per host — the buffer is handed to the engine via
    ``unpack_params`` + ``device_put`` (the TPU analogue of the reference's
    chunked host->GPU broadcast, patches.py:169-241).
    """

    def __init__(self, layout: ParamLayout, instance_endpoint: str,
                 sender_endpoint: str, num_streams: int = 4,
                 listen_host: str = "0.0.0.0", advertise_host: str | None = None,
                 reconnect_backoff_s: float = 0.2,
                 reconnect_backoff_max_s: float = 10.0,
                 shard_spec=None):
        self.layout = layout
        self.buffer = alloc_buffer(layout)
        # the engine's shard spec (transfer/layout.py ShardSpec), advertised
        # in the register message so the sender can build the trainer→engine
        # ReshardingMap for this receiver and fan the round over shard-owned
        # streams; None = replicated engine (tp=1)
        self.shard_spec = shard_spec
        self.instance_endpoint = instance_endpoint
        self.sender_host, self.sender_port = _split(sender_endpoint)
        self.sockets = ReceiverSockets(self.buffer, num_streams, listen_host)
        self.advertise_host = advertise_host or "127.0.0.1"
        self.version = -1
        self.error: str | None = None
        # sync-health telemetry (server_info "transfer_*" flat keys via
        # health(): a flapping control channel, rejected rounds, and the
        # resume traffic are all visible per engine)
        self.control_reconnects = 0
        self.verify_failures = 0   # rounds answered verify_failed
        self.rounds_verified = 0
        self.resumed_bytes = 0     # bytes landed via partial re-pushes
        self._reconnect_backoff_s = reconnect_backoff_s
        self._reconnect_backoff_max_s = reconnect_backoff_max_s
        self._armed_version = -1  # version of the round currently landing
        # held around every on_tensor emission batch (and the completion
        # tail): the prepare handler takes it before arming the NEXT round,
        # so a new push can never overwrite buffer bytes an installer is
        # still reading (torn-tensor guard for back-to-back syncs)
        self._install_lock = threading.Lock()
        self._version_cv = threading.Condition()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        backoff = self._reconnect_backoff_s
        while not self._stop.is_set():
            try:
                with socket.create_connection(
                        (self.sender_host, self.sender_port), timeout=30.0) as s:
                    backoff = self._reconnect_backoff_s
                    _send_json(s, {
                        "cmd": "register",
                        "instance": self.instance_endpoint,
                        "buffer_len": int(self.buffer.nbytes),
                        "host": self.advertise_host,
                        "ports": self.sockets.ports,
                        "shard_spec": (self.shard_spec.to_jsonable()
                                       if self.shard_spec is not None
                                       else None),
                    })
                    reader = _LineReader(s)
                    while not self._stop.is_set():
                        msg = reader.read(timeout=1.0)
                        if msg is None:
                            continue
                        if msg.get("event") == "prepare":
                            # serialize behind a mid-flight incremental
                            # install: its buffer reads must finish before
                            # this round's bytes land over them (sender
                            # retries if "ready" is delayed past its gate)
                            resume = msg.get("resume") or None
                            with self._install_lock:
                                with self._version_cv:
                                    self._armed_version = int(
                                        msg.get("version", -1))
                                self.sockets.arm(
                                    int(msg["round"]),
                                    reset=resume is None,
                                    clear=[(int(o), int(ln))
                                           for o, ln in resume]
                                    if resume else None)
                            _send_json(s, {"event": "ready",
                                           "instance": self.instance_endpoint})
                        elif msg.get("event") == "verify":
                            # verified install: coverage + manifest digests
                            # must check out against the landed buffer
                            # BEFORE the version installs; a failure
                            # answers the ranges the sender must re-push
                            ok, missing, detail = self._verify_round(msg)
                            _send_json(s, {
                                "event": "verify_result",
                                "instance": self.instance_endpoint,
                                "round": int(msg.get("round", -1)),
                                "version": int(msg.get("version", -1)),
                                "ok": ok,
                                "missing": [[o, ln] for o, ln in missing],
                                "error": detail,
                            })
                        elif msg.get("event") == "transfer_done":
                            # trusting path (transfer.verify=false) and the
                            # sender's best-effort failure notification
                            if msg.get("status") != "success":
                                log.error("transfer failed: %s", msg)
                                continue
                            self.sockets.wait(timeout=600.0)
                            with self._version_cv:
                                self.version = int(msg["version"])
                                self._version_cv.notify_all()
                        elif msg.get("event") == "error":
                            # permanent rejection (e.g. layout/buffer-size
                            # mismatch): surface loudly, stop retrying
                            self.error = str(msg.get("error", "unknown"))
                            log.error("sender rejected registration: %s",
                                      self.error)
                            return
            except (OSError, ConnectionError) as exc:
                if self._stop.is_set():
                    return
                # capped + jittered: a fleet of receivers losing one sender
                # must not reconnect in lockstep, and a dead sender must
                # not be hammered at 5 Hz forever
                self.control_reconnects += 1
                sleep = backoff * (0.5 + random.random())
                log.warning("receiver control reconnect #%d in %.2fs (%s)",
                            self.control_reconnects, sleep, exc)
                self._stop.wait(sleep)
                backoff = min(backoff * 2, self._reconnect_backoff_max_s)

    def _verify_round(self, msg: dict) -> tuple[bool, list, str]:
        """The receiver's side of the verify handshake: wait for the armed
        round's streams to terminate, then check the sender's manifest
        (range digests) AND full-buffer coverage against the ledger. Only
        a clean round installs the version — a corrupt or torn round is
        rejected *without* installing, and the answer carries exactly the
        ranges the sender must re-push."""
        rnd = int(msg.get("round", -1))
        version = int(msg.get("version", -1))
        manifest = [(int(o), int(ln), int(c))
                    for o, ln, c in msg.get("manifest") or []]
        wait_s = float(msg.get("wait_s", 30.0))
        if self.sockets._round != rnd:
            return False, [], (f"round {rnd} superseded by "
                               f"{self.sockets._round}")
        resume = self.sockets.resume_round
        # best-effort completion wait: a dead stream just leaves gaps,
        # which the ledger check below turns into resumable ranges
        self.sockets.wait_done(timeout=wait_s)
        missing = self.sockets.verify_ranges(manifest)
        if not missing:
            # belt and braces beyond the manifest: the union of verified
            # manifests must cover the whole buffer (gap detection)
            missing = self.sockets.gaps(int(self.buffer.nbytes))
        if missing:
            self.verify_failures += 1
            return False, missing, f"{len(missing)} ranges failed verify"
        if resume:
            self.resumed_bytes += sum(ln for _, ln, _ in manifest)
        self.rounds_verified += 1
        with self._version_cv:
            if version > self.version:
                self.version = version
            self._version_cv.notify_all()
        return True, [], ""

    def health(self) -> dict[str, int]:
        """Flat ``transfer_*`` sync-health keys for the rollout server's
        ``server_info`` (→ /statusz gauges): is this engine's receiver
        flapping, rejecting rounds, or riding resume traffic?"""
        return {
            "transfer_control_reconnects": int(self.control_reconnects),
            "transfer_crc_frame_failures": int(self.sockets.crc_failures),
            "transfer_verify_failures": int(self.verify_failures),
            "transfer_rounds_verified": int(self.rounds_verified),
            "transfer_resumed_bytes": int(self.resumed_bytes),
            "transfer_weight_version": int(self.version),
            "transfer_push_streams": len(self.sockets.ports),
            "transfer_shard_tp": int(self.shard_spec.num_shards
                                     if self.shard_spec else 1),
        }

    def wait_for_version(self, version: int, timeout: float = 600.0,
                         on_tensor=None) -> int:
        """Block until weights of at least ``version`` are in the buffer
        (the reference's 'receive_weights' wait, receiver_agent.py:257-268).
        Returns the version whose bytes were actually installed — ≥ the
        requested one when a superseding round landed instead (callers
        recording ``engine.weight_version`` must use the RETURN value, not
        the request, or they under-report until the next push).

        ``on_tensor(entry, np_view)``: incremental install hook — invoked
        IN LAYOUT ORDER for each tensor whose bytes have fully landed,
        while later tensors are still on the wire (overlaps the wire with
        the device upload; reference overlap: sender_agent.py:567-647).
        Landed bytes are final (streams send monotonically from a stable
        snapshot), so a completed tensor never changes within a round. If
        a retry/newer round supersedes the one being tailed, every tensor
        is re-emitted from the final buffer — the consumer must treat
        emissions as idempotent upserts by name.

        The install lock is dropped BETWEEN tensor emissions (advisor r4:
        ``on_tensor`` is a device_put that can take seconds, and the
        sender's prepare→ready gate is 60 s — holding the lock across a
        whole emission batch starved back-to-back pushes into spurious
        manager aborts). A prepare arriving between two tensors arms the
        new round; the next iteration observes it under the lock and stops
        reading the old bytes before any stream can overwrite them."""
        deadline = time.monotonic() + timeout
        emitted = 0
        tail_round = None
        from .layout import covered_entries

        def emit_landed() -> None:
            nonlocal emitted, tail_round
            if on_tensor is None:
                return
            while True:
                with self._version_cv:
                    armed = self._armed_version
                if armed != target:  # only tail the round we wait on
                    return
                with self._install_lock:
                    rnd = self.sockets._round
                    if rnd != tail_round:
                        tail_round, emitted = rnd, 0  # retry: start over
                    es = covered_entries(self.layout,
                                         self.sockets.coverage(), emitted,
                                         limit=1)
                    if not es:
                        return
                    e = es[0]  # ONE tensor per lock hold (see docstring)
                    on_tensor(e, self.buffer[e.offset : e.offset + e.nbytes])
                    emitted += 1

        target = version
        while True:
            with self._version_cv:
                while self.version < target:
                    if self._stop.is_set():
                        raise ConnectionError("receiver stopped")
                    if self.error is not None:
                        raise ConnectionError(
                            f"receiver registration rejected: {self.error}")
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise TimeoutError(
                            f"weights v{target} not received "
                            f"(have v{self.version})")
                    if on_tensor is not None:
                        self._version_cv.release()
                        try:
                            emit_landed()
                        finally:
                            self._version_cv.acquire()
                        self._version_cv.wait(min(left, 0.05))
                    else:
                        self._version_cv.wait(min(left, 1.0))
                final = self.version
            if on_tensor is None:
                return final
            # completion tail: emit the remaining entries, one lock hold
            # per tensor (the NEXT round's prepare waits out at most one
            # device_put, not the whole tail). The round id AND armed
            # version are re-read under the lock on EVERY iteration, and
            # emission is gated on the current round's landed coverage: a
            # SAME-version re-push (sender retry) arming mid-tail changes
            # sockets._round and resets coverage, which restarts the tail
            # and blocks it until the new round's bytes land — without
            # this the tail would keep emitting buffer ranges the retry's
            # streams are actively overwriting (advisor r5; the old code
            # only checked the round once and leaned on the implicit
            # byte-identical-same-version invariant).
            superseded = False
            if final != target:
                emitted, tail_round = 0, None  # stale pre-wait progress
            while not superseded:
                progressed = False
                with self._install_lock:
                    with self._version_cv:
                        armed = self._armed_version
                        cur = self.version
                    if armed > cur or cur != final:
                        # a SUPERSEDING round armed (streams will land over
                        # the buffer) — or armed AND completed within one
                        # inter-tensor lock gap (cur moved past the version
                        # this tail was emitting): either way the remaining
                        # bytes are not round-``final``'s — restart the
                        # tail against the newest version (still "at least
                        # version"). Without the ``cur != final`` arm a
                        # fully-landed supersede would mix two versions'
                        # tensors into one install.
                        target = max(armed, cur)
                        emitted, tail_round = 0, None
                        superseded = True
                        continue
                    rnd = self.sockets._round
                    if rnd != tail_round:
                        # re-push of the SAME version restarted the round:
                        # start over against its (reset) coverage
                        tail_round, emitted = rnd, 0
                    if emitted >= len(self.layout.entries):
                        return final
                    es = covered_entries(self.layout,
                                         self.sockets.coverage(), emitted,
                                         limit=1)
                    if es:
                        e = es[0]
                        on_tensor(e,
                                  self.buffer[e.offset : e.offset + e.nbytes])
                        emitted += 1
                        progressed = True
                if not progressed:
                    # mid re-push: the next entry's bytes have not landed
                    # yet — wait for stream progress instead of emitting
                    # bytes that are being overwritten
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"weights v{final} install tail stalled behind "
                            f"an incomplete re-push")
                    with self._version_cv:
                        self._version_cv.wait(0.05)

    def stop(self) -> None:
        self._stop.set()
        with self._version_cv:
            self._version_cv.notify_all()  # break waiting installers out
        self.sockets.close()
        if self._thread:
            self._thread.join(timeout=5.0)


# --------------------------------------------------------------------------
# Sender
# --------------------------------------------------------------------------


@dataclass
class _Registration:
    instance: str
    host: str
    ports: list[int]
    sock: socket.socket
    lock: threading.Lock = field(default_factory=threading.Lock)
    ready: threading.Event = field(default_factory=threading.Event)
    # verify handshake response slot: _handle_conn parks the receiver's
    # verify_result here and sets the event; _push_one round-checks it
    verify_evt: threading.Event = field(default_factory=threading.Event)
    verify_msg: dict | None = None
    pushed_version: int = -1
    # the engine's advertised ShardSpec (None = replicated) and the cached
    # per-stream assignment plan built from it on first push — invalidated
    # only by re-registration, since layout and spec are both immutable for
    # a registration's lifetime
    shard_spec: object | None = None
    stream_plan: list | None = None
    reshard_total: int = 0


class SenderAgent:
    """Trainer-side transfer agent (thread; reference uses an mp.Process,
    sender_agent.py:682-694 — a thread suffices since pack/send release the
    GIL and lets the trainer overlap transfer with the next step)."""

    def __init__(self, buffer: np.ndarray, manager_client=None,
                 listen_host: str = "0.0.0.0", num_streams: int = 4,
                 poll_s: float = 1.0, advertise_host: str | None = None,
                 bind_host: str | None = None,
                 cfg: TransferConfig | None = None, fault=None,
                 layout: ParamLayout | None = None,
                 trainer_spec=None):
        self.buffer = buffer
        self.manager = manager_client
        self.cfg = cfg or TransferConfig()
        # sharded-push inputs: with a layout, each receiver's advertised
        # ShardSpec yields a ReshardingMap whose stream_assignments fan the
        # round over num_streams shard-owned range lists (layout=None keeps
        # the legacy contiguous split)
        self.layout = layout
        self.trainer_spec = trainer_spec
        # transfer-plane chaos injector (rollout/faults.py); interruptible
        # on stop() so a sleeping stall never pins teardown
        self.fault = fault
        # bind_host pins this sender's outbound data streams to one NIC
        # (SenderGroup runs one agent per interface for aggregate
        # bandwidth). Worker headroom beyond num_streams: multi-instance
        # fan-out shares this pool, and one instance's stalled stream must
        # not head-of-line-block another instance's sends into a spurious
        # deadline miss.
        self.engine = TcpTransferEngine(num_streams=num_streams,
                                        workers=max(num_streams * 4, 8),
                                        bind_host=bind_host)
        self._notify_pool = ThreadPoolExecutor(max_workers=4)
        # per-instance push fan-out: an executor (not bare threads) so
        # teardown mid-push can cancel queued pushes (cancel_futures) and
        # the conftest thread-leak guard sees pool workers, not strays
        self._push_pool = ThreadPoolExecutor(max_workers=16)
        self.poll_s = poll_s
        self.reg_wait_s = 10.0
        self.version = -1
        self._regs: dict[str, _Registration] = {}
        self._regs_lock = threading.Lock()
        # supervision ledgers (under _regs_lock): per-instance sync health
        # for /statusz, and the escalated-instances blocklist that stops a
        # laggard from being re-pushed at the same version every poll
        self._health: dict[str, dict] = {}
        self._escalated: dict[str, int] = {}  # instance -> version
        self._cmds: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        # (buffer, version) pairing protocol: a push round snapshots both
        # under _cv with _inflight+=1; a swap/pack waits for _inflight==0.
        # Packing into a DIFFERENT (back) buffer overlaps with in-flight
        # rounds — only the pointer swap synchronizes (the reference gets
        # this overlap from its agent process, sender_agent.py:682-694).
        self._cv = threading.Condition()
        self._inflight = 0
        self._packing = False
        self._watermark = None  # streaming push: gates sends behind the pack
        self._poisoned_version = -1  # streamed pack died: never push this
        self._round_counter = 0  # unique per push attempt (stale-stream guard)
        # laggard escalation hook: called as cb(instance, reason) when an
        # instance exhausts its retry budget (train.py wires
        # PoolManager.escalate_laggard — drain + deregister)
        self.laggard_cb = None
        # supervision telemetry (cumulative; TransferInterface.counters()
        # folds these into transfer/* step-record gauges)
        self.push_failures = 0       # failed push attempts (any cause)
        self.push_retries = 0        # attempts re-run after a failure
        self.verify_failures = 0     # attempts rejected by receiver verify
        self.resumed_bytes = 0       # bytes re-pushed via partial resumes
        self.rounds_verified = 0     # verified installs
        self.laggard_escalations = 0
        # elastic-pool telemetry: full pushes to instances this sender had
        # never pushed before — the scale-up catch-up path (a late joiner
        # registers, the idle poll finds it stale, it gets the CURRENT
        # version in one round, then rides the normal push fan-out)
        self.catchup_pushes = 0
        # sharded-push telemetry: streams the last round fanned over, the
        # slowest stream's bandwidth that round (the round's critical path),
        # cumulative bytes carried on shard-pair-owned ranges, and how many
        # individual stream failures were converted into partial resumes
        # instead of full re-pushes
        self.push_streams = 0
        self.stream_bw_mbps_min = 0.0
        self.reshard_bytes = 0
        self.stream_resumes = 0
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((listen_host, 0))
        self._server.listen(64)
        self.control_port = self._server.getsockname()[1]
        self.endpoint = f"{advertise_host or _advertise_ip()}:{self.control_port}"
        self._threads: list[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        for target in (self._accept_loop, self._event_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self.fault is not None:
            # wake any injected stall so teardown never waits it out
            self.fault.stop()
        try:
            self._server.close()
        except OSError:
            pass
        # break registered control channels: blocked handshake waits and
        # the receivers' readers return immediately instead of timing out
        with self._regs_lock:
            regs = list(self._regs.values())
        for reg in regs:
            try:
                reg.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self.engine.shutdown()
        self._push_pool.shutdown(wait=False, cancel_futures=True)
        self._notify_pool.shutdown(wait=False, cancel_futures=True)
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()

    # -- trainer API --------------------------------------------------------

    def signal_update(self, version: int | None = None) -> int:
        """Trainer signals new weights are packed (in-place into
        ``self.buffer``); returns the new version."""
        with self._cv:
            while self._inflight > 0:
                self._cv.wait()
            self.version = version if version is not None else self.version + 1
            self._watermark = None
            v = self.version
        self._cmds.put("update_weights")
        return v

    def signal_update_streaming(self, watermark,
                                version: int | None = None) -> int:
        """Streaming push: announce the version BEFORE packing; sends are
        gated behind ``watermark`` while the caller packs in place into
        ``self.buffer`` (the watermark orders buffer access: senders read
        only below it, the packer writes only above it). The reference's
        in-round sender pipeline (sender_agent.py:567-647)."""
        with self._cv:
            while self._inflight > 0:
                self._cv.wait()
            self.version = version if version is not None else self.version + 1
            self._watermark = watermark
            v = self.version
        self._cmds.put("update_weights")
        return v

    def mark_push_failed(self, version: int) -> None:
        """A streamed pack died mid-round: the buffer holds garbage for
        ``version``. Poison it so the poll loop stops re-pushing it every
        ``poll_s`` (each retry would fail at the watermark and spam the
        manager with aborts); the next successful signal/swap resumes."""
        with self._cv:
            self._poisoned_version = version
        log.error("weight push v%d poisoned (pack failed); waiting for a "
                  "new update", version)

    def swap_buffer(self, new_buffer: np.ndarray, version: int) -> np.ndarray:
        """Atomically install a freshly packed buffer; returns the old one
        (double-buffering: the caller packs the next update into it)."""
        with self._cv:
            while self._inflight > 0:
                self._cv.wait()
            old, self.buffer = self.buffer, new_buffer
            self.version = version
            self._watermark = None
        self._cmds.put("update_weights")
        return old

    class _PackGuard:
        def __init__(self, sender: "SenderAgent"):
            self._s = sender

        def __enter__(self):
            with self._s._cv:
                while self._s._inflight > 0 or self._s._packing:
                    self._s._cv.wait()
                self._s._packing = True

        def __exit__(self, *exc):
            with self._s._cv:
                self._s._packing = False
                self._s._cv.notify_all()

    def buffer_write_lock(self) -> "_PackGuard":
        """Guard for packing in place into ``self.buffer`` (direct mode);
        blocks while a push round is in flight and vice versa."""
        return SenderAgent._PackGuard(self)

    # -- registration server ------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        reader = _LineReader(conn)
        reg: _Registration | None = None
        try:
            while not self._stop.is_set():
                msg = reader.read(timeout=1.0)
                if msg is None:
                    continue
                if msg.get("cmd") == "register":
                    if int(msg["buffer_len"]) != int(self.buffer.nbytes):
                        _send_json(conn, {"event": "error",
                                          "error": "buffer size mismatch"})
                        return
                    reg = _Registration(instance=msg["instance"],
                                        host=msg["host"],
                                        ports=list(msg["ports"]), sock=conn,
                                        shard_spec=ShardSpec.from_jsonable(
                                            msg.get("shard_spec")))
                    with self._regs_lock:
                        self._regs[reg.instance] = reg
                        # a fresh registration clears any standing laggard
                        # escalation: a restarted/recovered receiver gets a
                        # fresh retry budget
                        self._escalated.pop(reg.instance, None)
                    _send_json(conn, {"event": "registered",
                                      "version": self.version})
                    log.info("receiver registered: %s", reg.instance)
                elif msg.get("event") == "ready" and reg is not None:
                    reg.ready.set()
                elif msg.get("event") == "verify_result" and reg is not None:
                    reg.verify_msg = msg
                    reg.verify_evt.set()
        except (ConnectionError, OSError):
            pass
        finally:
            if reg is not None:
                with self._regs_lock:
                    if self._regs.get(reg.instance) is reg:
                        del self._regs[reg.instance]

    # -- event loop (pull model) --------------------------------------------

    def _event_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._cmds.get(timeout=self.poll_s)
            except queue.Empty:
                pass  # idle poll — late joiners (sender_agent.py:324-340)
            if self._stop.is_set():
                return
            if self.version < 0:
                continue
            try:
                self._check_and_update_receivers()
            except Exception:  # noqa: BLE001 — keep the loop alive
                log.exception("weight push round failed")

    def _stale_instances(self, version: int) -> list[str]:
        if self.manager is None:
            with self._regs_lock:
                stale = [i for i, r in self._regs.items()
                         if r.pushed_version < version]
        else:
            resp = self.manager.get_receive_instances(self.endpoint)
            stale = [i["endpoint"] if isinstance(i, dict) else i
                     for i in resp.get("instances", [])]
        # escalated laggards are dead capacity at this version: the
        # laggard callback drains+deregisters them, but until that lands
        # (and forever in manager-less mode) the poll must not re-push
        # them every poll_s. A NEW version or a fresh registration clears
        # the blocklist entry.
        with self._regs_lock:
            esc = dict(self._escalated)
        return [i for i in stale if esc.get(i) != version]

    def _wait_registration(self, instance: str) -> _Registration | None:
        """Bootstrap race: the manager may hand us an instance whose receiver
        hasn't connected yet (the reference's wait_for_receiver_registration,
        sender_agent.py:342-351)."""
        deadline = time.monotonic() + self.reg_wait_s
        while time.monotonic() < deadline and not self._stop.is_set():
            with self._regs_lock:
                reg = self._regs.get(instance)
            if reg is not None:
                return reg
            time.sleep(0.05)
        return None

    def _check_and_update_receivers(self) -> None:
        # snapshot (buffer, version) atomically; the round holds an inflight
        # ref so swaps/packs wait, but packing the BACK buffer proceeds in
        # parallel with the sends.
        with self._cv:
            while self._packing:
                self._cv.wait()
            version = self.version
            buffer = self.buffer
            watermark = self._watermark
            if version == self._poisoned_version:
                return  # failed streamed pack: nothing valid to push
            self._inflight += 1
        try:
            stale = self._stale_instances(version)
            if not stale:
                return
            futures = [self._push_pool.submit(self._push_instance, i,
                                              version, buffer, watermark)
                       for i in stale]
            for f in futures:
                f.result()
        finally:
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()

    def _abort_on_manager(self, instance: str) -> None:
        """Clear the manager's updating_weight CAS so the instance is
        retried next poll instead of being drained forever."""
        if self.manager is not None:
            try:
                self._notify_pool.submit(self.manager.abort_weight_update,
                                         [instance])
            except RuntimeError:
                # agent closing: notify pool already shut down; the manager
                # side times the CAS out on its own
                pass

    def _note_health(self, instance: str, inc: dict | None = None,
                     **set_kv) -> None:
        """Fold one event into the per-instance sync-health ledger (the
        ``transfer`` block of the /statusz pool section)."""
        with self._regs_lock:
            h = self._health.setdefault(instance, {
                "pushed_version": -1, "push_failures": 0,
                "verify_failures": 0, "resumed_bytes": 0,
                "stream_resumes": 0,
                "last_push_s": None, "escalated": False, "last_error": ""})
            for k, v in (inc or {}).items():
                h[k] = h.get(k, 0) + v
            h.update(set_kv)

    def sync_health(self) -> dict[str, dict]:
        """Per-instance push health: ``{endpoint: {pushed_version,
        push_failures, verify_failures, resumed_bytes, last_push_s,
        escalated, registered, last_error}}`` — PoolManager merges this
        into the /statusz pool section's engine rows."""
        with self._regs_lock:
            regs = set(self._regs)
            esc = set(self._escalated)
            out = {i: dict(h) for i, h in self._health.items()}
        for i in regs:
            out.setdefault(i, {})
        for i, h in out.items():
            h["registered"] = i in regs
            h["escalated"] = bool(h.get("escalated")) or i in esc
        return out

    def counters(self) -> dict[str, float]:
        """Cumulative ``transfer/*`` supervision gauges for step records."""
        return {
            "transfer/push_failures": float(self.push_failures),
            "transfer/push_retries": float(self.push_retries),
            "transfer/verify_failures": float(self.verify_failures),
            "transfer/resumed_bytes": float(self.resumed_bytes),
            "transfer/rounds_verified": float(self.rounds_verified),
            "transfer/laggard_escalations": float(self.laggard_escalations),
            "transfer/catchup_pushes": float(self.catchup_pushes),
            "transfer/push_streams": float(self.push_streams),
            "transfer/stream_bw_mbps_min": float(self.stream_bw_mbps_min),
            "transfer/reshard_bytes": float(self.reshard_bytes),
            "transfer/stream_resumes": float(self.stream_resumes),
        }

    def _escalate(self, instance: str, version: int, err: str) -> None:
        """Retry budget exhausted: the instance is a laggard — dead
        capacity the bootstrap gate already holds out of routing. Stop
        re-pushing it (same-version blocklist) and hand it to the fleet
        control plane (PoolManager.escalate_laggard drains + deregisters).
        Without a callback the manager CAS is cleared so a FUTURE version
        may retry — but the blocklist stops the every-``poll_s`` re-push
        of this one."""
        self.laggard_escalations += 1
        self._note_health(instance, escalated=True, last_error=err)
        log.error("weight push to %s exhausted its retry budget at v%d "
                  "(%s); escalating laggard", instance, version, err)
        with self._regs_lock:
            self._escalated[instance] = version
        cb = self.laggard_cb
        if cb is not None:
            try:
                # off the push thread: the callback drains + deregisters
                # over HTTP and must not block the round's fan-out join
                self._notify_pool.submit(cb, instance, err)
            except RuntimeError:
                pass  # agent closing
        else:
            self._abort_on_manager(instance)

    def _push_instance(self, instance: str, version: int,
                       buffer: np.ndarray, watermark=None) -> None:
        """Supervised push: attempts = 1 + retry_budget, each under the
        bandwidth-keyed deadline, separated by jittered exponential
        backoff. A ``verify_failed`` attempt resumes — the next attempt
        re-pushes ONLY the failed ranges; a transport failure re-pushes in
        full. Budget exhaustion escalates the laggard."""
        cfg = self.cfg
        missing: list[tuple[int, int]] | None = None
        registered_once = False
        last_err = ""
        attempt = 0
        while not self._stop.is_set():
            reg = self._wait_registration(instance)
            if reg is None:
                if not registered_once:
                    # bootstrap race, not a laggard: the manager handed us
                    # an instance whose receiver never connected. Clear
                    # the CAS so a later poll retries once it registers.
                    log.error("no receiver registration for %s; "
                              "skipping push", instance)
                    self._abort_on_manager(instance)
                    return
                last_err = "receiver registration lost"
                missing = None
            else:
                registered_once = True
                try:
                    missing, rejected = self._push_one(reg, version, buffer,
                                                       watermark,
                                                       ranges=missing)
                    if not missing:
                        return  # verified + installed
                    if rejected:
                        # the RECEIVER rejected landed bytes (digest/gap
                        # check) — distinct from a sender-side stream
                        # failure, which resumes without being a verify
                        # failure (the fabric didn't reject clean bytes)
                        self.verify_failures += 1
                        self._note_health(instance,
                                          inc={"verify_failures": 1})
                        last_err = f"verify_failed ({len(missing)} ranges)"
                    else:
                        last_err = f"stream_failed ({len(missing)} ranges)"
                    log.warning("push v%d to %s incomplete: %s",
                                version, instance, last_err)
                except Exception as exc:  # noqa: BLE001 — retried below
                    last_err = f"{type(exc).__name__}: {exc}"
                    missing = None  # transport failure: full re-push
                    self._notify_transfer_failed(reg, version, last_err)
                    log.error("push v%d to %s failed: %s", version,
                              instance, last_err)
            self.push_failures += 1
            self._note_health(instance, inc={"push_failures": 1},
                              last_error=last_err)
            attempt += 1
            if attempt > cfg.retry_budget:
                self._escalate(instance, version, last_err)
                return
            self.push_retries += 1
            sleep = min(cfg.backoff_base_s * (2 ** (attempt - 1)),
                        cfg.backoff_max_s) * (0.5 + random.random())
            if self._stop.wait(sleep):
                return

    @staticmethod
    def _notify_transfer_failed(reg: _Registration, version: int,
                                err: str) -> None:
        """Best-effort failure notice so the receiver's log shows cause."""
        try:
            _send_json(reg.sock, {"event": "transfer_done",
                                  "status": "failure", "version": version,
                                  "error": err})
        except OSError:
            pass

    def _stream_plan(self, reg: _Registration):
        """Lazily build (and cache on the registration) the sharded
        per-stream assignment plan for this receiver: the trainer→engine
        :class:`~polyrl_tpu.transfer.layout.ReshardingMap` packed into
        min(num_streams, receiver ports) balanced range lists. None when
        the sender has no layout (legacy contiguous split)."""
        if self.layout is None or self.layout.total_bytes != self.buffer.nbytes:
            return None
        if reg.stream_plan is None:
            rmap = build_resharding_map(self.layout, self.trainer_spec,
                                        reg.shard_spec)
            n = min(self.engine.num_streams, len(reg.ports)) or 1
            reg.stream_plan = rmap.stream_assignments(n)
            reg.reshard_total = rmap.reshard_bytes()
        return reg.stream_plan

    def _collect_streams(self, batch, t0: float, streamed: bool):
        """Per-stream supervision of one wire round: each stream is waited
        under its OWN bandwidth-keyed deadline (anchored at ``t0`` — the
        streams run concurrently). Returns (manifest, missing_pre, errors):
        the concatenated frame manifests of the streams that landed, the
        full assigned ranges of those that didn't (re-pushed on resume —
        a dead stream's partially-landed tail is not trusted), and one
        error string per failed stream."""
        cfg = self.cfg
        manifest: list[tuple[int, int, int]] = []
        missing_pre: list[tuple[int, int]] = []
        errors: list[str] = []
        bw_min = None
        for i, fut in enumerate(batch.futures):
            assigned = (batch.assignments[i]
                        if i < len(batch.assignments) else [])
            sbytes = sum(ln for _, ln in assigned)
            dl = cfg.stream_deadline_s(sbytes, streamed)
            remaining = (t0 + dl) - time.monotonic()
            try:
                manifest.extend(fut.result(timeout=max(0.05, remaining))
                                or [])
                dt = time.monotonic() - t0
                if sbytes and dt > 0:
                    bw = sbytes / dt / 1e6
                    bw_min = bw if bw_min is None else min(bw_min, bw)
            except Exception as exc:  # noqa: BLE001 — per-stream resume
                errors.append(f"stream {i}: {type(exc).__name__}: {exc}")
                missing_pre.extend(assigned)
        self.push_streams = len(batch.futures)
        if bw_min is not None:
            self.stream_bw_mbps_min = round(bw_min, 3)
        return manifest, missing_pre, errors

    def _push_one(self, reg: _Registration, version: int,
                  buffer: np.ndarray, watermark=None,
                  ranges: list[tuple[int, int]] | None = None,
                  ) -> tuple[list[tuple[int, int]], bool]:
        """One push attempt: prepare/arm, fan the wire over N streams each
        under its own bandwidth-keyed deadline, then the verify handshake.
        Returns ``(missing, rejected)``: ``([], _)`` on a verified install;
        otherwise the merged ranges to resume — the failed streams' full
        assignments plus whatever the receiver's digest/gap check rejected
        — with ``rejected`` True only when the RECEIVER rejected bytes the
        sender believed landed. Raises on transport failure (every stream
        failed, control channel dead, ...)."""
        cfg = self.cfg
        with self._cv:
            self._round_counter += 1
            round_id = self._round_counter
        streamed = watermark is not None
        # sharded fan-out applies to full packed rounds; resumes carry the
        # failed ranges round-robin, and watermark rounds keep the STRIPE
        # interleave (a shard-grouped slab would idle every stream whose
        # slab the packer hadn't reached — the exact serialization the
        # stripe assignment exists to avoid)
        plan = None
        if ranges is None and not streamed:
            plan = self._stream_plan(reg)
        push_bytes = (sum(ln for _, ln in ranges) if ranges
                      else buffer.nbytes)
        deadline = cfg.push_deadline_s(push_bytes, streamed=streamed)
        with reg.lock:
            reg.ready.clear()
            reg.verify_evt.clear()
            reg.verify_msg = None
            prep = {"event": "prepare", "version": version,
                    "round": round_id}
            if ranges:
                # resume: the receiver keeps the superseded round's
                # coverage and clears only these ranges
                prep["resume"] = [[o, ln] for o, ln in ranges]
            _send_json(reg.sock, prep)
            if not reg.ready.wait(timeout=cfg.prepare_timeout_s):
                raise TimeoutError("receiver did not arm listeners")
            t0 = time.monotonic()
            if self.fault is not None:
                self.fault.note_attempt(reg.instance)
            batch = self.engine.transfer_submit_write(
                reg.host, reg.ports, buffer, round_id=round_id,
                watermark=watermark, ranges=ranges,
                gate_timeout_s=deadline + 1.0,
                fault=self.fault, instance=reg.instance,
                assignments=plan)
            manifest, missing_pre, errors = self._collect_streams(
                batch, t0, streamed)
            if errors and len(errors) == len(batch.futures):
                raise ConnectionError(
                    f"all {len(batch.futures)} streams failed: {errors[0]}")
            if (self.fault is not None
                    and self.fault.take_control_kill(reg.instance)):
                # chaos: control-plane death right before the verify
                # handshake — the receiver must reconnect, the retry
                # must re-push the round
                try:
                    reg.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            if cfg.verify:
                _send_json(reg.sock, {
                    "event": "verify", "round": round_id,
                    "version": version,
                    "manifest": [[o, ln, c] for o, ln, c in manifest],
                    # receiver-side completion wait for straggler frames
                    # still in the kernel after our futures resolved
                    "wait_s": min(30.0, deadline),
                })
                evt_deadline = time.monotonic() + deadline + 30.0
                while not reg.verify_evt.wait(timeout=0.2):
                    if self._stop.is_set():
                        raise ConnectionError("sender stopping")
                    if time.monotonic() > evt_deadline:
                        raise TimeoutError(
                            "receiver never answered verify")
                vr = reg.verify_msg or {}
                if int(vr.get("round", -1)) != round_id:
                    raise ConnectionError("verify result round mismatch")
                if vr.get("ok"):
                    # full coverage verified — even a timed-out stream's
                    # bytes landed and digest-checked (the receiver has
                    # already installed the version; treat as success)
                    missing = []
                    missing_pre = []
                    errors = []
                else:
                    missing = [(int(o), int(ln))
                               for o, ln in vr.get("missing") or []]
                    if not missing and not missing_pre:
                        raise ConnectionError(
                            "verify failed without resumable ranges: "
                            f"{vr.get('error')}")
            else:
                if errors:
                    # the trusting path has no verify round to scope a
                    # partial resume — a lost stream fails the attempt
                    raise ConnectionError(
                        f"{len(errors)} streams failed: {errors[0]}")
                # trusting path: bare completion installs the version
                _send_json(reg.sock, {"event": "transfer_done",
                                      "status": "success",
                                      "version": version})
                missing = []
            dt = time.monotonic() - t0
        if errors:
            # individual stream failures become a partial resume instead
            # of a full re-push: only those streams' ranges return
            self.stream_resumes += len(errors)
            self._note_health(reg.instance,
                              inc={"stream_resumes": len(errors)})
        if missing or missing_pre:
            rejected = bool(missing) and not errors
            return _merge_ranges(missing + missing_pre), rejected
        if ranges:
            resumed = sum(ln for _, ln in ranges)
            self.resumed_bytes += resumed
            self._note_health(reg.instance, inc={"resumed_bytes": resumed})
        if plan is not None:
            self.reshard_bytes += reg.reshard_total
        self.rounds_verified += 1
        if reg.pushed_version < 0:
            self.catchup_pushes += 1
        reg.pushed_version = version
        with self._regs_lock:
            self._escalated.pop(reg.instance, None)
        self._note_health(reg.instance, pushed_version=version,
                          last_push_s=round(dt, 4), escalated=False)
        mbps = push_bytes / max(dt, 1e-9) / 1e6
        # per-instance push duration distribution: one slow receiver
        # (bad NIC, busy engine) shows up as a p99/max outlier that the
        # fleet-wide MB/s mean would average away
        obs.observe("transfer/push_s", dt)
        log.info("pushed v%d to %s: %.0f MB/s over %d stream(s)%s", version,
                 reg.instance, mbps, max(1, self.push_streams),
                 " (resume)" if ranges else "")
        if self.manager is not None:
            # async notify so the instance rejoins the pool without the
            # trainer's next pack blocking on the engine's weight load
            # (sender_agent.py:617-624)
            self._notify_pool.submit(
                self.manager.update_weights, [reg.instance], version)
        return [], False


class SenderGroup:
    """N sender agents, one per local NIC, sharing one packed buffer.

    The reference fans each trainer's weight push over
    ``num_mooncake_groups_per_sender`` engine groups bound to different
    node IPs (config.toml:19-20, fsdp_interface.py:97-138) so an 8B push
    saturates aggregate NIC bandwidth, not one interface. Here each group
    is a full :class:`SenderAgent` (own control endpoint + TCP engine
    source-bound to its NIC); the MANAGER partitions rollout instances
    across the groups when all endpoints are registered via
    ``PUT /update_weight_senders`` — per-group work is 1/N of the pool.

    The buffer is shared read-only during pushes; trainer-side mutation
    (``signal_update`` / ``swap_buffer`` / ``buffer_write_lock``) fans out
    to every agent so each agent's (buffer, version) snapshot invariant is
    preserved independently.
    """

    def __init__(self, buffer: np.ndarray, sender_ips: list[str],
                 manager_client=None, num_streams: int = 4,
                 poll_s: float = 1.0, listen_host: str = "0.0.0.0",
                 cfg: TransferConfig | None = None, fault=None,
                 layout: ParamLayout | None = None, trainer_spec=None):
        if not sender_ips:
            raise ValueError("SenderGroup needs at least one sender IP")
        self.manager = manager_client
        self.senders = [
            SenderAgent(buffer, manager_client=manager_client,
                        listen_host=listen_host, num_streams=num_streams,
                        poll_s=poll_s, advertise_host=ip, bind_host=ip,
                        cfg=cfg, fault=fault, layout=layout,
                        trainer_spec=trainer_spec)
            for ip in sender_ips
        ]

    @property
    def laggard_cb(self):
        return self.senders[0].laggard_cb

    @laggard_cb.setter
    def laggard_cb(self, cb) -> None:
        for s in self.senders:
            s.laggard_cb = cb

    def counters(self) -> dict[str, float]:
        """Fleet-summed ``transfer/*`` gauges across the per-NIC agents."""
        out: dict[str, float] = {}
        for s in self.senders:
            for k, v in s.counters().items():
                out[k] = out.get(k, 0.0) + v
        return out

    def sync_health(self) -> dict[str, dict]:
        """Per-instance health; the manager partitions instances across
        the groups, so the per-agent dicts are disjoint by construction."""
        out: dict[str, dict] = {}
        for s in self.senders:
            out.update(s.sync_health())
        return out

    @property
    def endpoints(self) -> list[str]:
        return [s.endpoint for s in self.senders]

    @property
    def version(self) -> int:
        return self.senders[0].version

    @property
    def buffer(self) -> np.ndarray:
        return self.senders[0].buffer

    def mark_push_failed(self, version: int) -> None:
        for s in self.senders:
            s.mark_push_failed(version)

    def start(self) -> None:
        for s in self.senders:
            s.start()

    def stop(self) -> None:
        for s in self.senders:
            s.stop()

    def signal_update(self, version: int | None = None) -> int:
        v = self.senders[0].signal_update(version)
        for s in self.senders[1:]:
            s.signal_update(v)
        return v

    def swap_buffer(self, new_buffer: np.ndarray, version: int) -> np.ndarray:
        old = self.senders[0].swap_buffer(new_buffer, version)
        for s in self.senders[1:]:
            s.swap_buffer(new_buffer, version)
        return old

    @contextlib.contextmanager
    def buffer_write_lock(self):
        """All-agents pack guard (no push round may be in flight on ANY
        NIC while the shared buffer is rewritten in place)."""
        with contextlib.ExitStack() as stack:
            for s in self.senders:
                stack.enter_context(s.buffer_write_lock())
            yield


def _split(endpoint: str) -> tuple[str, int]:
    host, port = endpoint.rsplit(":", 1)
    return host, int(port)


def _advertise_ip() -> str:
    from .nic import default_route_ip

    return default_route_ip()
