"""Flat parameter layout: name -> (shape, dtype, offset) over one buffer.

TPU-native equivalent of the reference's flat meta layout computed from the
FSDP state dict (reference: rlboost/weight_transfer/fsdp_interface.py:141-154
builds meta tensors; sender_agent.py:235-309 sizes one contiguous buffer).
Here the source of truth is a JAX param pytree: we flatten it with tree
paths, lay entries out contiguously (64-byte aligned so receivers can view
slices as arrays cheaply), and pack/unpack through host numpy views.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any

import jax
import numpy as np

ALIGN = 64


def _dtype_name(dt) -> str:
    return np.dtype(dt).name if not str(dt).startswith("bfloat16") else "bfloat16"


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return ".".join(parts)


@dataclass(frozen=True)
class Entry:
    name: str
    shape: tuple[int, ...]
    dtype: str  # numpy dtype name, or "bfloat16"
    offset: int
    nbytes: int


@dataclass(frozen=True)
class ParamLayout:
    entries: tuple[Entry, ...]
    total_bytes: int

    def by_name(self) -> dict[str, Entry]:
        return {e.name: e for e in self.entries}

    def to_json(self) -> str:
        return json.dumps({
            "total_bytes": self.total_bytes,
            "entries": [
                [e.name, list(e.shape), e.dtype, e.offset, e.nbytes]
                for e in self.entries
            ],
        })

    @staticmethod
    def from_json(s: str) -> "ParamLayout":
        d = json.loads(s)
        entries = tuple(
            Entry(n, tuple(sh), dt, off, nb) for n, sh, dt, off, nb in d["entries"]
        )
        return ParamLayout(entries, d["total_bytes"])


def build_layout(params: Any) -> ParamLayout:
    """Compute the flat layout from a pytree of arrays/ShapeDtypeStructs."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    entries = []
    offset = 0
    for path, leaf in leaves:
        name = _path_str(path)
        shape = tuple(int(s) for s in leaf.shape)
        dtype = _dtype_name(leaf.dtype)
        nbytes = int(np.prod(shape, dtype=np.int64)) * _np_dtype(dtype).itemsize
        entries.append(Entry(name, shape, dtype, offset, nbytes))
        offset += (nbytes + ALIGN - 1) // ALIGN * ALIGN
    return ParamLayout(tuple(entries), offset)


def alloc_buffer(layout: ParamLayout) -> np.ndarray:
    """One contiguous uint8 host buffer for the whole layout."""
    return np.zeros(layout.total_bytes, dtype=np.uint8)


def pack_params(params: Any, layout: ParamLayout, buffer: np.ndarray) -> None:
    """Gather params to host and copy into the buffer at layout offsets.

    Device->host transfers run via ``jax.device_get`` on the whole tree at
    once (batched DMA), mirroring the reference's non-blocking GPU->shm copy
    loop (fsdp_interface.py:186-207).
    """
    host = jax.device_get(params)
    leaves = jax.tree_util.tree_flatten_with_path(host)[0]
    by_name = layout.by_name()
    for path, leaf in leaves:
        e = by_name[_path_str(path)]
        arr = np.asarray(leaf)
        view = buffer[e.offset : e.offset + e.nbytes].view(_np_dtype(e.dtype))
        view[:] = arr.reshape(-1)


def pack_params_streaming(params: Any, layout: ParamLayout,
                          buffer: np.ndarray, progress,
                          group_bytes: int = 64 << 20) -> None:
    """Pack in layout order, advancing ``progress(high_water_byte)`` after
    each ~``group_bytes`` group so sender streams can trail the packer
    (one push round overlaps pack and wire; pack_params gates the whole
    wire on the full device->host gather instead).

    ``copy_to_host_async`` is issued for every leaf up front, so the
    per-group ``device_get`` drains transfers that are already in flight —
    the D2H path stays bandwidth-bound, not round-trip-bound."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    by_name = {_path_str(p): leaf for p, leaf in leaves}
    for leaf in by_name.values():
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()
    group: list[Entry] = []
    size = 0

    def flush() -> None:
        nonlocal group, size
        if not group:
            return
        host = jax.device_get([by_name[e.name] for e in group])
        for e, arr in zip(group, host):
            view = buffer[e.offset : e.offset + e.nbytes].view(
                _np_dtype(e.dtype))
            view[:] = np.asarray(arr).reshape(-1)
        progress(group[-1].offset + group[-1].nbytes)
        group, size = [], 0

    for e in layout.entries:
        group.append(e)
        size += e.nbytes
        if size >= group_bytes:
            flush()
    flush()
    progress(layout.total_bytes)


def covered_entries(layout: ParamLayout, coverage, start_idx: int = 0,
                    limit: int | None = None):
    """Entries from ``start_idx`` whose bytes are fully landed, given
    receive-side ``coverage`` = sorted (range_offset, bytes_landed) pairs
    (ReceiverSockets.coverage()). Stops at the first incomplete entry so
    callers emit tensors strictly in layout order. ``limit`` caps the
    result (per-tensor install loops want just the next one — building the
    full list each lock hold is O(entries²) over a round)."""
    # landed prefixes of contiguous stream ranges: merge adjacent so an
    # entry spanning a range boundary is recognised once both sides land
    merged: list[list[int]] = []
    for off, got in coverage:
        if got <= 0:
            continue
        if merged and off <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], off + got)
        else:
            merged.append([off, off + got])
    out = []
    i = 0
    for e in layout.entries[start_idx:]:
        lo, hi = e.offset, e.offset + e.nbytes
        while i < len(merged) and merged[i][1] <= lo:
            i += 1
        if i < len(merged) and merged[i][0] <= lo and hi <= merged[i][1]:
            out.append(e)
            if limit is not None and len(out) >= limit:
                break
        else:
            break
    return out


def make_incremental_installer(template: Any):
    """Build (install_fn, device_named) for a streaming weight install:
    ``install_fn(entry, raw_bytes)`` device_puts one landed tensor with the
    template leaf's dtype — and its sharding when the leaf is a committed
    device array. ONE implementation shared by the rollout server's
    update_weights_from_agent and bench_weight_sync, so the bench measures
    exactly the production install path."""
    tmpl = {_path_str(p): leaf for p, leaf in
            jax.tree_util.tree_flatten_with_path(template)[0]}
    device_named: dict[str, Any] = {}

    def install(entry: Entry, raw) -> None:
        old = tmpl[entry.name]
        host = np.asarray(raw).view(_np_dtype(entry.dtype)).reshape(
            entry.shape)
        sharding = getattr(old, "sharding", None)
        if sharding is not None:
            device_named[entry.name] = jax.device_put(
                host.astype(old.dtype), sharding)
        else:
            device_named[entry.name] = jax.device_put(host.astype(old.dtype))

    return install, device_named


def unpack_params(buffer: np.ndarray, layout: ParamLayout) -> dict[str, np.ndarray]:
    """Zero-copy views into the buffer, name -> ndarray."""
    out = {}
    for e in layout.entries:
        out[e.name] = (
            buffer[e.offset : e.offset + e.nbytes]
            .view(_np_dtype(e.dtype))
            .reshape(e.shape)
        )
    return out


def unflatten_like(template: Any, named: dict[str, np.ndarray]) -> Any:
    """Rebuild a pytree with ``template``'s structure from named arrays."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = [named[_path_str(p)] for p, _ in paths]
    return jax.tree_util.tree_unflatten(treedef, leaves)
