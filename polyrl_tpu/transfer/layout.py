"""Flat parameter layout: name -> (shape, dtype, offset) over one buffer.

TPU-native equivalent of the reference's flat meta layout computed from the
FSDP state dict (reference: rlboost/weight_transfer/fsdp_interface.py:141-154
builds meta tensors; sender_agent.py:235-309 sizes one contiguous buffer).
Here the source of truth is a JAX param pytree: we flatten it with tree
paths, lay entries out contiguously (64-byte aligned so receivers can view
slices as arrays cheaply), and pack/unpack through host numpy views.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any

import jax
import numpy as np

ALIGN = 64


def _dtype_name(dt) -> str:
    return np.dtype(dt).name if not str(dt).startswith("bfloat16") else "bfloat16"


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return ".".join(parts)


@dataclass(frozen=True)
class Entry:
    name: str
    shape: tuple[int, ...]
    dtype: str  # numpy dtype name, or "bfloat16"
    offset: int
    nbytes: int


@dataclass(frozen=True)
class ParamLayout:
    entries: tuple[Entry, ...]
    total_bytes: int

    def by_name(self) -> dict[str, Entry]:
        return {e.name: e for e in self.entries}

    def to_json(self) -> str:
        return json.dumps({
            "total_bytes": self.total_bytes,
            "entries": [
                [e.name, list(e.shape), e.dtype, e.offset, e.nbytes]
                for e in self.entries
            ],
        })

    @staticmethod
    def from_json(s: str) -> "ParamLayout":
        d = json.loads(s)
        entries = tuple(
            Entry(n, tuple(sh), dt, off, nb) for n, sh, dt, off, nb in d["entries"]
        )
        return ParamLayout(entries, d["total_bytes"])


def build_layout(params: Any) -> ParamLayout:
    """Compute the flat layout from a pytree of arrays/ShapeDtypeStructs."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    entries = []
    offset = 0
    for path, leaf in leaves:
        name = _path_str(path)
        shape = tuple(int(s) for s in leaf.shape)
        dtype = _dtype_name(leaf.dtype)
        nbytes = int(np.prod(shape, dtype=np.int64)) * _np_dtype(dtype).itemsize
        entries.append(Entry(name, shape, dtype, offset, nbytes))
        offset += (nbytes + ALIGN - 1) // ALIGN * ALIGN
    return ParamLayout(tuple(entries), offset)


def alloc_buffer(layout: ParamLayout) -> np.ndarray:
    """One contiguous uint8 host buffer for the whole layout."""
    return np.zeros(layout.total_bytes, dtype=np.uint8)


def pack_params(params: Any, layout: ParamLayout, buffer: np.ndarray) -> None:
    """Gather params to host and copy into the buffer at layout offsets.

    Device->host transfers run via ``jax.device_get`` on the whole tree at
    once (batched DMA), mirroring the reference's non-blocking GPU->shm copy
    loop (fsdp_interface.py:186-207).
    """
    host = jax.device_get(params)
    leaves = jax.tree_util.tree_flatten_with_path(host)[0]
    by_name = layout.by_name()
    for path, leaf in leaves:
        e = by_name[_path_str(path)]
        arr = np.asarray(leaf)
        view = buffer[e.offset : e.offset + e.nbytes].view(_np_dtype(e.dtype))
        view[:] = arr.reshape(-1)


def pack_params_streaming(params: Any, layout: ParamLayout,
                          buffer: np.ndarray, progress,
                          group_bytes: int = 64 << 20) -> None:
    """Pack in layout order, advancing ``progress(high_water_byte)`` after
    each ~``group_bytes`` group so sender streams can trail the packer
    (one push round overlaps pack and wire; pack_params gates the whole
    wire on the full device->host gather instead).

    ``copy_to_host_async`` is issued for every leaf up front, so the
    per-group ``device_get`` drains transfers that are already in flight —
    the D2H path stays bandwidth-bound, not round-trip-bound."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    by_name = {_path_str(p): leaf for p, leaf in leaves}
    for leaf in by_name.values():
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()
    group: list[Entry] = []
    size = 0

    def flush() -> None:
        nonlocal group, size
        if not group:
            return
        host = jax.device_get([by_name[e.name] for e in group])
        for e, arr in zip(group, host):
            view = buffer[e.offset : e.offset + e.nbytes].view(
                _np_dtype(e.dtype))
            view[:] = np.asarray(arr).reshape(-1)
        progress(group[-1].offset + group[-1].nbytes)
        group, size = [], 0

    for e in layout.entries:
        group.append(e)
        size += e.nbytes
        if size >= group_bytes:
            flush()
    flush()
    progress(layout.total_bytes)


def covered_entries(layout: ParamLayout, coverage, start_idx: int = 0,
                    limit: int | None = None):
    """Entries from ``start_idx`` whose bytes are fully landed, given
    receive-side ``coverage`` = sorted (range_offset, bytes_landed) pairs
    (ReceiverSockets.coverage()). Stops at the first incomplete entry so
    callers emit tensors strictly in layout order. ``limit`` caps the
    result (per-tensor install loops want just the next one — building the
    full list each lock hold is O(entries²) over a round)."""
    # landed prefixes of contiguous stream ranges: merge adjacent so an
    # entry spanning a range boundary is recognised once both sides land
    merged: list[list[int]] = []
    for off, got in coverage:
        if got <= 0:
            continue
        if merged and off <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], off + got)
        else:
            merged.append([off, off + got])
    out = []
    i = 0
    for e in layout.entries[start_idx:]:
        lo, hi = e.offset, e.offset + e.nbytes
        while i < len(merged) and merged[i][1] <= lo:
            i += 1
        if i < len(merged) and merged[i][0] <= lo and hi <= merged[i][1]:
            out.append(e)
            if limit is not None and len(out) >= limit:
                break
        else:
            break
    return out


def make_incremental_installer(template: Any):
    """Build (install_fn, device_named) for a streaming weight install:
    ``install_fn(entry, raw_bytes)`` device_puts one landed tensor with the
    template leaf's dtype — and its sharding when the leaf is a committed
    device array. ONE implementation shared by the rollout server's
    update_weights_from_agent and bench_weight_sync, so the bench measures
    exactly the production install path."""
    tmpl = {_path_str(p): leaf for p, leaf in
            jax.tree_util.tree_flatten_with_path(template)[0]}
    device_named: dict[str, Any] = {}

    def install(entry: Entry, raw) -> None:
        old = tmpl[entry.name]
        host = np.asarray(raw).view(_np_dtype(entry.dtype)).reshape(
            entry.shape)
        sharding = getattr(old, "sharding", None)
        if sharding is not None:
            device_named[entry.name] = jax.device_put(
                host.astype(old.dtype), sharding)
        else:
            device_named[entry.name] = jax.device_put(host.astype(old.dtype))

    return install, device_named


# --------------------------------------------------------------------------
# Sharded weight fabric: trainer→engine resharding map
# --------------------------------------------------------------------------

# An entry sharded along a non-leading axis fragments into one byte range
# per outer block (prod(shape[:axis]) of them). Past this many ranges the
# per-stream manifests stop paying for shard affinity — the entry falls
# back to the replicated round-robin pool (coarse ALIGN-granular chunks),
# which changes stream/shard affinity but never coverage or correctness.
MAX_RANGES_PER_ENTRY = 256

# owner id for bytes no single (trainer, engine) shard pair owns:
# replicated entries, range-explosion fallbacks and alignment padding
POOL = -1


@dataclass(frozen=True)
class ShardSpec:
    """How one side of the fabric shards the flat layout's entries.

    ``num_shards`` is the shard count of the mesh axis (engine ``tp``,
    trainer ``fsdp``); ``axes`` maps entry name -> the tensor axis sharded
    over it (absent/None = replicated on that side). Wire-format friendly:
    receivers advertise it in their register message so the sender can
    build a :class:`ReshardingMap` per registration.
    """

    num_shards: int
    axes: dict[str, int | None]

    def axis_of(self, name: str) -> int | None:
        if self.num_shards <= 1:
            return None
        return self.axes.get(name)

    def to_jsonable(self) -> dict:
        return {"num_shards": int(self.num_shards),
                "axes": {k: v for k, v in self.axes.items()
                         if v is not None}}

    @staticmethod
    def from_jsonable(d: dict | None) -> "ShardSpec | None":
        if not d:
            return None
        return ShardSpec(int(d.get("num_shards", 1)),
                         {k: int(v) for k, v in d.get("axes", {}).items()})


def build_shard_spec(params: Any, axis: str = "tp") -> ShardSpec:
    """Derive a :class:`ShardSpec` from a pytree of (possibly) mesh-sharded
    jax arrays: for each leaf, the tensor axis whose PartitionSpec names
    ``axis``. Leaves without a NamedSharding (host arrays, single-device)
    and leaves whose spec never names ``axis`` are replicated."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    num_shards = 1
    axes: dict[str, int | None] = {}
    for path, leaf in leaves:
        name = _path_str(path)
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        mesh = getattr(sharding, "mesh", None)
        found = None
        if spec is not None and mesh is not None and axis in mesh.shape:
            num_shards = max(num_shards, int(mesh.shape[axis]))
            for dim, names in enumerate(spec):
                if names is None:
                    continue
                group = names if isinstance(names, tuple) else (names,)
                if axis in group:
                    found = dim
                    break
        axes[name] = found
    return ShardSpec(num_shards, axes)


def _shard_ranges(e: Entry, axis: int | None, n: int):
    """Absolute (offset, length) byte ranges each of ``n`` shards owns of
    entry ``e`` when sharded along tensor ``axis`` (row-major flat layout).
    Returns None when the split doesn't apply cleanly (replicated, n==1,
    non-divisible dim, or range explosion past MAX_RANGES_PER_ENTRY) —
    callers then route the entry to the pool."""
    if axis is None or n <= 1:
        return None
    if axis >= len(e.shape) or e.shape[axis] % n != 0:
        return None
    outer = int(np.prod(e.shape[:axis], dtype=np.int64)) if axis else 1
    if outer > MAX_RANGES_PER_ENTRY:
        return None
    item = _np_dtype(e.dtype).itemsize
    inner = (int(np.prod(e.shape[axis + 1:], dtype=np.int64))
             if axis + 1 < len(e.shape) else 1) * item
    d = e.shape[axis]
    per = (d // n) * inner
    out = []
    for j in range(n):
        rs = []
        for o in range(outer):
            off = e.offset + o * d * inner + j * per
            if rs and rs[-1][0] + rs[-1][1] == off:
                rs[-1] = (rs[-1][0], rs[-1][1] + per)
            else:
                rs.append((off, per))
        out.append(rs)
    return out


def _intersect(a: list[tuple[int, int]], b: list[tuple[int, int]]):
    """Intersection of two sorted disjoint (offset, length) range lists."""
    out, i, j = [], 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][0] + a[i][1], b[j][0] + b[j][1])
        if lo < hi:
            out.append((lo, hi - lo))
        if a[i][0] + a[i][1] <= b[j][0] + b[j][1]:
            i += 1
        else:
            j += 1
    return out


@dataclass(frozen=True)
class ReshardingMap:
    """Per-byte ownership of the flat layout across (trainer shard →
    engine shard) pairs: ``atoms`` is a disjoint, offset-sorted cover of
    ``[0, total_bytes)`` as (offset, length, trainer_shard, engine_shard)
    with :data:`POOL` (-1) marking replicated/padding bytes. Built by
    :func:`build_resharding_map`; consumed by :meth:`stream_assignments`
    to fan a push round over N concurrent streams."""

    total_bytes: int
    num_trainer_shards: int
    num_engine_shards: int
    atoms: tuple[tuple[int, int, int, int], ...]

    def reshard_bytes(self) -> int:
        """Bytes with a real (non-pool) shard-pair owner."""
        return sum(ln for _, ln, t, e in self.atoms
                   if t != POOL or e != POOL)

    def stream_assignments(self, num_streams: int):
        """Pack the atoms into ``num_streams`` offset-sorted, coalesced
        (offset, length) lists: disjoint union covering [0, total_bytes),
        each stream carrying at most ceil(total/num_streams) + ALIGN
        bytes. Atoms are laid out pair-grouped (all of (t0,e0) first, ...)
        with the pool round-robined by the greedy fill, so a stream
        usually carries whole shard-pairs; atoms split only at ALIGN
        boundaries to keep resume ranges cheap to verify."""
        n = max(1, int(num_streams))
        if self.total_bytes == 0:
            return [[] for _ in range(n)]
        target = -(-self.total_bytes // n)
        ordered = sorted(
            self.atoms,
            key=lambda a: ((1, 0, 0) if a[2] == POOL and a[3] == POOL
                           else (0, a[2], a[3]), a[0]))
        streams: list[list[tuple[int, int]]] = [[] for _ in range(n)]
        fill = [0] * n
        s = 0
        for off, ln, _t, _e in ordered:
            while ln > 0:
                if fill[s] >= target and s < n - 1:
                    s += 1
                room = target - fill[s]
                if room >= ln or s == n - 1:
                    take = ln
                else:
                    # split at an ALIGN boundary, rounding UP so the
                    # stream overshoots target by < ALIGN instead of
                    # leaving an un-splittable sliver
                    take = min(ln, -(-room // ALIGN) * ALIGN)
                streams[s].append((off, take))
                fill[s] += take
                off += take
                ln -= take
        for rs in streams:
            rs.sort()
            i = 1
            while i < len(rs):
                if rs[i - 1][0] + rs[i - 1][1] == rs[i][0]:
                    rs[i - 1] = (rs[i - 1][0], rs[i - 1][1] + rs[i][1])
                    del rs[i]
                else:
                    i += 1
        return streams


def build_resharding_map(layout: ParamLayout,
                         trainer_spec: ShardSpec | None,
                         engine_spec: ShardSpec | None) -> ReshardingMap:
    """Compute byte ownership of ``layout`` from the trainer's shard spec
    and the engine's: for each entry, the intersection of trainer shard
    i's ranges with engine shard j's. Replicated-on-both-sides entries,
    non-divisible splits, range explosions and alignment padding all land
    in the POOL. The atom set always covers [0, total_bytes) exactly —
    the receiver's gap verifier demands full coverage."""
    t_n = trainer_spec.num_shards if trainer_spec else 1
    e_n = engine_spec.num_shards if engine_spec else 1
    atoms: list[tuple[int, int, int, int]] = []
    for k, e in enumerate(layout.entries):
        t_ranges = _shard_ranges(
            e, trainer_spec.axis_of(e.name) if trainer_spec else None, t_n)
        e_ranges = _shard_ranges(
            e, engine_spec.axis_of(e.name) if engine_spec else None, e_n)
        if t_ranges is None and e_ranges is None:
            atoms.append((e.offset, e.nbytes, POOL, POOL))
        elif t_ranges is None:
            for j, rs in enumerate(e_ranges):
                atoms.extend((o, ln, POOL, j) for o, ln in rs)
        elif e_ranges is None:
            for i, rs in enumerate(t_ranges):
                atoms.extend((o, ln, i, POOL) for o, ln in rs)
        else:
            for i, trs in enumerate(t_ranges):
                for j, ers in enumerate(e_ranges):
                    atoms.extend((o, ln, i, j)
                                 for o, ln in _intersect(trs, ers))
        # alignment padding up to the next entry (or total_bytes)
        end = e.offset + e.nbytes
        nxt = (layout.entries[k + 1].offset if k + 1 < len(layout.entries)
               else layout.total_bytes)
        if nxt > end:
            atoms.append((end, nxt - end, POOL, POOL))
    atoms.sort()
    return ReshardingMap(layout.total_bytes, t_n, e_n, tuple(atoms))


def pack_params_ranges(params: Any, layout: ParamLayout,
                       buffer: np.ndarray,
                       ranges: list[tuple[int, int]]) -> None:
    """Range-restricted pack: copy into ``buffer`` only the bytes covered
    by ``ranges`` (sorted, disjoint), gathering to host ONLY the entries
    the ranges intersect — the per-shard path of the sharded push, where
    each stream packs its own slice of the layout instead of every stream
    waiting on a full-tree gather. For leaves mesh-sharded along axis 0
    the copy reads the owning shard's host data directly
    (``addressable_shards`` — no cross-shard gather); other leaves fall
    back to a one-entry ``device_get``."""
    if not ranges:
        return
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    by_name = {_path_str(p): leaf for p, leaf in leaves}
    ri = 0
    for e in layout.entries:
        lo, hi = e.offset, e.offset + e.nbytes
        while ri < len(ranges) and ranges[ri][0] + ranges[ri][1] <= lo:
            ri += 1
        need = []
        j = ri
        while j < len(ranges) and ranges[j][0] < hi:
            r_lo = max(lo, ranges[j][0])
            r_hi = min(hi, ranges[j][0] + ranges[j][1])
            if r_lo < r_hi:
                need.append((r_lo, r_hi))
            j += 1
        if not need:
            continue
        leaf = by_name[e.name]
        flat = None
        shards = getattr(leaf, "addressable_shards", None)
        item = _np_dtype(e.dtype).itemsize
        if shards is not None and len(shards) > 1:
            # axis-0 shards are contiguous flat blocks — serve each needed
            # range from the shard(s) that own it, host-copying shard data
            # only (np.asarray on shard.data is the shard's bytes, not the
            # global array)
            blocks = []
            ok = True
            for sh in shards:
                idx = sh.index[0] if sh.index else slice(None)
                start = idx.start or 0
                inner = (int(np.prod(e.shape[1:], dtype=np.int64))
                         if len(e.shape) > 1 else 1) * item
                b_lo = lo + start * inner
                data = sh.data
                b_hi = b_lo + data.size * item
                rest = sh.index[1:] if sh.index else ()
                if any(not (isinstance(s, slice) and s == slice(None))
                       for s in rest):
                    ok = False  # sharded beyond axis 0 — not flat blocks
                    break
                blocks.append((b_lo, b_hi, data))
            if ok:
                for r_lo, r_hi in need:
                    for b_lo, b_hi, data in blocks:
                        c_lo, c_hi = max(r_lo, b_lo), min(r_hi, b_hi)
                        if c_lo >= c_hi:
                            continue
                        src = np.asarray(data).reshape(-1).view(np.uint8)
                        buffer[c_lo:c_hi] = src[c_lo - b_lo:c_hi - b_lo]
                continue
        flat = np.asarray(jax.device_get(leaf)).reshape(-1).view(np.uint8)
        for r_lo, r_hi in need:
            buffer[r_lo:r_hi] = flat[r_lo - lo:r_hi - lo]


def make_sharded_installer(template: Any):
    """Like :func:`make_incremental_installer` but for a mesh-sharded
    engine (tp>1): entries whose template leaf spans multiple devices are
    installed shard-by-shard — per device, slice the landed bytes by the
    sharding's index map, cast, ``device_put`` to THAT device only, then
    assemble with ``jax.make_array_from_single_device_arrays``. Peak extra
    host memory is one shard (not one full tensor), and no full-size
    single-device array is ever materialized on the serving side.
    Single-device leaves take the plain incremental path."""
    tmpl = {_path_str(p): leaf for p, leaf in
            jax.tree_util.tree_flatten_with_path(template)[0]}
    device_named: dict[str, Any] = {}

    def install(entry: Entry, raw) -> None:
        old = tmpl[entry.name]
        sharding = getattr(old, "sharding", None)
        idx_map = None
        if sharding is not None and getattr(old, "ndim", 0) > 0:
            try:
                devs = sharding.addressable_devices_indices_map(entry.shape)
                if len(devs) > 1:
                    idx_map = devs
            except (AttributeError, TypeError, ValueError):
                idx_map = None
        host = np.asarray(raw).view(_np_dtype(entry.dtype)).reshape(
            entry.shape)
        if idx_map is None:  # single-device / replicated: incremental path
            if sharding is not None:
                device_named[entry.name] = jax.device_put(
                    host.astype(old.dtype), sharding)
            else:
                device_named[entry.name] = jax.device_put(
                    host.astype(old.dtype))
            return
        pieces = []
        for dev, idx in idx_map.items():
            piece = np.ascontiguousarray(host[idx]).astype(old.dtype)
            pieces.append(jax.device_put(piece, dev))
        device_named[entry.name] = jax.make_array_from_single_device_arrays(
            entry.shape, sharding, pieces)

    return install, device_named


def unpack_params(buffer: np.ndarray, layout: ParamLayout) -> dict[str, np.ndarray]:
    """Zero-copy views into the buffer, name -> ndarray."""
    out = {}
    for e in layout.entries:
        out[e.name] = (
            buffer[e.offset : e.offset + e.nbytes]
            .view(_np_dtype(e.dtype))
            .reshape(e.shape)
        )
    return out


def unflatten_like(template: Any, named: dict[str, np.ndarray]) -> Any:
    """Rebuild a pytree with ``template``'s structure from named arrays."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = [named[_path_str(p)] for p, _ in paths]
    return jax.tree_util.tree_unflatten(treedef, leaves)
