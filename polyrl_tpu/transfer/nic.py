"""NIC enumeration + CIDR selection for multi-interface weight transfer.

TPU-VM equivalent of the reference's sender-IP selection
(``rlboost/weight_transfer/fsdp_interface.py:97-138``: enumerate node IPs,
filter by the ``allowed_sender_ips`` CIDR config, round-robin groups over
the surviving interfaces). Multi-NIC TPU hosts (e.g. v5e VMs expose several
VPC interfaces) only reach aggregate bandwidth when each sender group binds
a different interface — a single socket rides one NIC.

Pure stdlib: interface addresses come from ``SIOCGIFADDR`` ioctls (Linux),
CIDR math from ``ipaddress``.
"""

from __future__ import annotations

import array
import ipaddress
import socket
import struct


def get_node_ips(include_loopback: bool = False) -> list[str]:
    """IPv4 addresses of all up interfaces on this host (reference
    ``get_node_ips``). Falls back to the default-route IP on failure."""
    ips: list[str] = []
    try:
        import fcntl

        # SIOCGIFCONF: list interfaces (works without netlink/psutil)
        max_ifaces = 64
        bufsize = max_ifaces * 40
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            buf = array.array("B", b"\0" * bufsize)
            ifconf = struct.pack("iL", bufsize, buf.buffer_info()[0])
            out = fcntl.ioctl(s.fileno(), 0x8912, ifconf)  # SIOCGIFCONF
            nbytes = struct.unpack("iL", out)[0]
            data = bytes(buf[:nbytes])
        # each ifreq is 40 bytes on 64-bit linux: 16 name + sockaddr
        for off in range(0, nbytes, 40):
            ip = socket.inet_ntoa(data[off + 20 : off + 24])
            if not include_loopback and ip.startswith("127."):
                continue
            if ip not in ips:
                ips.append(ip)
    except (OSError, ImportError, ValueError):
        pass
    if not ips:
        ips = [default_route_ip()]
    return ips


def default_route_ip() -> str:
    """IP of the interface holding the default route (UDP-connect trick;
    no packet is sent). Shared with the sender's advertise-endpoint logic."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def filter_ips_by_cidr(ips: list[str], cidr_spec: str) -> list[str]:
    """Keep IPs inside any CIDR of the comma-separated ``cidr_spec``
    (reference ``filter_ips_by_config``). Empty/0.0.0.0/0 keeps all."""
    spec = (cidr_spec or "").strip()
    if not spec or spec == "0.0.0.0/0":
        return list(ips)
    nets = [ipaddress.ip_network(c.strip(), strict=False)
            for c in spec.split(",") if c.strip()]
    return [ip for ip in ips
            if any(ipaddress.ip_address(ip) in n for n in nets)]


def pick_sender_ips(num_groups: int, cidr_spec: str = "",
                    ips: list[str] | None = None) -> list[str]:
    """One bind/advertise IP per sender group: filtered node IPs,
    round-robined up to ``num_groups`` (reference fsdp_interface.py:108-115
    — fewer NICs than groups wraps around; more NICs truncates)."""
    node_ips = ips if ips is not None else get_node_ips(include_loopback=True)
    filtered = filter_ips_by_cidr(node_ips, cidr_spec)
    # advertising 127.0.0.1 to remote receivers is never useful when a real
    # interface matched the CIDR too (with the default open CIDR the bare
    # enumeration would otherwise put loopback first)
    non_loop = [ip for ip in filtered if not ip.startswith("127.")]
    if non_loop:
        filtered = non_loop
    if not filtered:
        raise RuntimeError(
            f"no node IP matches sender CIDR {cidr_spec!r} (node IPs: "
            f"{node_ips})")
    if len(filtered) < num_groups:
        filtered = (filtered * (num_groups // len(filtered) + 1))
    return filtered[:num_groups]
