"""Trainer-side weight-transfer facade.

TPU-native equivalent of the reference's FSDPInterface
(rlboost/weight_transfer/fsdp_interface.py:47-233): computes the flat
layout from the param pytree, owns the packed host buffer and the sender
agent, and per update (a) bumps the manager's weight version (which
atomically drains the active pool, fsdp_interface.py:80-95), (b) gathers
params to host into the buffer, (c) signals the sender agent.

Two paths:
- ``TransferInterface`` — cross-host (DCN) push over the TCP fabric, for
  disaggregated rollout pools.
- ``colocated_update`` — in-slice reshard: ``jax.device_put`` with the
  rollout mesh sharding (the TPU analogue of the reference's NCCL TP
  broadcast, which disappears into GSPMD).
"""

from __future__ import annotations

import logging
import time
from typing import Any

from .agents import SenderAgent
from .layout import ParamLayout, alloc_buffer, build_layout, pack_params

log = logging.getLogger(__name__)


class TransferInterface:
    def __init__(self, params_template: Any, manager_client=None,
                 num_streams: int = 4, poll_s: float = 1.0,
                 advertise_host: str | None = None):
        self.layout: ParamLayout = build_layout(params_template)
        self.buffer = alloc_buffer(self.layout)
        self.sender = SenderAgent(self.buffer, manager_client=manager_client,
                                  num_streams=num_streams, poll_s=poll_s,
                                  advertise_host=advertise_host)
        self.manager = manager_client
        self.sender.start()
        if manager_client is not None:
            manager_client.update_weight_senders([self.sender.endpoint])

    def update_weights_with_agent(self, params: Any) -> int:
        """Push new weights: version bump -> pack -> signal sender.

        The manager version bump, the pack, and the sender's version are all
        set under the sender's buffer lock: the poll loop reads (version,
        buffer) under the same lock, so it can never pair the new version
        with the old bytes or vice versa.
        """
        t0 = time.monotonic()
        with self.sender.buffer_write_lock():
            if self.manager is not None:
                version = self.manager.update_weight_version()
            else:
                version = self.sender.version + 1
            pack_params(params, self.layout, self.buffer)
            self.sender.version = version
        self.sender.wake()
        log.info("packed weights v%d (%.0f MB) in %.2fs", version,
                 self.buffer.nbytes / 1e6, time.monotonic() - t0)
        return version

    def close(self) -> None:
        self.sender.stop()


def colocated_update(engine, params: Any, version: int | None = None) -> None:
    """In-process hand-off to a colocated rollout engine (device_put with the
    engine's shardings — SURVEY §2.2: 'TP broadcast disappears into GSPMD')."""
    engine.update_weights(params, version=version)
