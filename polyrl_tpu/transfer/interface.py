"""Trainer-side weight-transfer facade.

TPU-native equivalent of the reference's FSDPInterface
(rlboost/weight_transfer/fsdp_interface.py:47-233): computes the flat
layout from the param pytree, owns the packed host buffer and the sender
agent, and per update (a) bumps the manager's weight version (which
atomically drains the active pool, fsdp_interface.py:80-95), (b) gathers
params to host into the buffer, (c) signals the sender agent.

Two paths:
- ``TransferInterface`` — cross-host (DCN) push over the TCP fabric, for
  disaggregated rollout pools.
- ``colocated_update`` — in-slice reshard: ``jax.device_put`` with the
  rollout mesh sharding (the TPU analogue of the reference's NCCL TP
  broadcast, which disappears into GSPMD).
"""

from __future__ import annotations

import logging
import time
from typing import Any

import numpy as np

from polyrl_tpu import obs

from .agents import SenderAgent, SenderGroup
from .layout import ParamLayout, alloc_buffer, build_layout, pack_params
from .nic import pick_sender_ips

log = logging.getLogger(__name__)


class TransferInterface:
    def __init__(self, params_template: Any, manager_client=None,
                 num_streams: int = 4, poll_s: float = 1.0,
                 advertise_host: str | None = None,
                 sender_groups: int = 1, sender_nic_cidr: str = "",
                 groups_per_sender: int = 1):
        self.layout: ParamLayout = build_layout(params_template)
        # serial mode double-buffers: pack into _back while the sender
        # pushes from its front buffer (lazy — the default streamed mode
        # packs in place and never needs the second copy of the weights)
        self._back: np.ndarray | None = None
        front = alloc_buffer(self.layout)
        if sender_groups > 1:
            # multi-NIC fan-out: one sender agent per interface (CIDR-picked
            # like the reference's 4-groups×8-engines layout,
            # fsdp_interface.py:97-138); the manager partitions the pool
            # across the advertised endpoints. ``advertise_host`` does not
            # apply here — each group advertises ITS OWN NIC's IP (use
            # sender_nic_cidr to steer which interfaces are picked).
            ips = pick_sender_ips(sender_groups, sender_nic_cidr)
            self.sender: SenderAgent | SenderGroup = SenderGroup(
                front, ips, manager_client=manager_client,
                num_streams=num_streams, poll_s=poll_s)
            endpoints = self.sender.endpoints
        else:
            self.sender = SenderAgent(front, manager_client=manager_client,
                                      num_streams=num_streams, poll_s=poll_s,
                                      advertise_host=advertise_host)
            endpoints = [self.sender.endpoint]
        self.manager = manager_client
        self.sender.start()
        if manager_client is not None:
            manager_client.update_weight_senders(
                endpoints, groups_per_sender=groups_per_sender)

    def update_weights_with_agent(self, params: Any,
                                  streaming: bool = True) -> int:
        """Push new weights. Two modes:

        - ``streaming`` (default): version bump FIRST, then pack in place
          while sender streams trail the pack watermark — pack, wire, and
          (with a receiver-side ``on_tensor`` installer) the device upload
          all overlap inside the one round. This is what the <5 s
          trainer->rollout sync latency KPI measures (reference in-round
          pipeline: sender_agent.py:567-647).
        - serial: pack into the back buffer (overlapping any in-flight
          PREVIOUS round), then swap. Kept for multi-NIC sender groups
          (each group streams a different NIC; one shared watermark would
          serialize them on the slowest pack reader).

        Either way the manager version bump drains the active pool
        (fsdp_interface.py:80-95) and only re-activates instances that
        reach the CURRENT version, so a racing old-version push can never
        leave an instance serving stale weights.
        """
        t0 = time.monotonic()
        with obs.span("transfer/update_weights",
                      mb=round(self.layout.total_bytes / 1e6, 1)):
            version = self._update_weights_impl(params, streaming)
        # trainer-side pack+signal time; the wire time per instance is
        # observed sender-side as transfer/push_s (agents._push_one)
        obs.observe("transfer/pack_s", time.monotonic() - t0)
        return version

    def _update_weights_impl(self, params: Any, streaming: bool) -> int:
        t0 = time.monotonic()
        if streaming and isinstance(self.sender, SenderAgent):
            from .layout import pack_params_streaming
            from .tcp_engine import Watermark

            if self.manager is not None:
                version = self.manager.update_weight_version()
            else:
                version = self.sender.version + 1
            wm = Watermark(self.layout.total_bytes)
            # waits for in-flight rounds, then arms (buffer, version, wm)
            self.sender.signal_update_streaming(wm, version)
            try:
                pack_params_streaming(params, self.layout,
                                      self.sender.buffer, wm.advance)
            except BaseException as exc:
                wm.fail(str(exc))  # unblock gated streams -> round fails
                # and stop the poll loop from re-pushing the garbage round
                self.sender.mark_push_failed(version)
                raise
            wm.finish()
        else:
            if self._back is None:
                self._back = alloc_buffer(self.layout)
            pack_params(params, self.layout, self._back)
            if self.manager is not None:
                version = self.manager.update_weight_version()
            else:
                version = self.sender.version + 1
            self._back = self.sender.swap_buffer(self._back, version)
        log.info("packed weights v%d (%.0f MB) in %.2fs", version,
                 self.layout.total_bytes / 1e6, time.monotonic() - t0)
        return version

    def close(self) -> None:
        self.sender.stop()


def colocated_update(engine, params: Any, version: int | None = None) -> None:
    """In-process hand-off to a colocated rollout engine (device_put with the
    engine's shardings — SURVEY §2.2: 'TP broadcast disappears into GSPMD')."""
    engine.update_weights(params, version=version)
