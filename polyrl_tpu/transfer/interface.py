"""Trainer-side weight-transfer facade.

TPU-native equivalent of the reference's FSDPInterface
(rlboost/weight_transfer/fsdp_interface.py:47-233): computes the flat
layout from the param pytree, owns the packed host buffer and the sender
agent, and per update (a) bumps the manager's weight version (which
atomically drains the active pool, fsdp_interface.py:80-95), (b) gathers
params to host into the buffer, (c) signals the sender agent.

Two paths:
- ``TransferInterface`` — cross-host (DCN) push over the TCP fabric, for
  disaggregated rollout pools.
- ``colocated_update`` — in-slice reshard: ``jax.device_put`` with the
  rollout mesh sharding (the TPU analogue of the reference's NCCL TP
  broadcast, which disappears into GSPMD).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any

import numpy as np

from polyrl_tpu import obs

from .agents import SenderAgent, SenderGroup, TransferConfig
from .layout import (ParamLayout, alloc_buffer, build_layout,
                     build_shard_spec, pack_params, pack_params_ranges)
from .nic import pick_sender_ips

log = logging.getLogger(__name__)


class TransferInterface:
    def __init__(self, params_template: Any, manager_client=None,
                 num_streams: int = 4, poll_s: float = 1.0,
                 advertise_host: str | None = None,
                 sender_groups: int = 1, sender_nic_cidr: str = "",
                 groups_per_sender: int = 1,
                 cfg: TransferConfig | None = None, fault=None):
        self.layout: ParamLayout = build_layout(params_template)
        # trainer-side shard spec (fsdp-axis): feeds the sender's
        # ReshardingMap so each push stream carries the (trainer shard →
        # engine shard) ranges it owns. Host-array templates come back
        # replicated (num_shards=1) — the map then has one trainer side.
        self.trainer_spec = build_shard_spec(params_template, axis="fsdp")
        # supervision knobs (config ``transfer.*``) + optional transfer-
        # plane fault injector (rollout/faults.py TransferFaultInjector)
        self.cfg = cfg or TransferConfig()
        self.fault = fault
        # serial mode double-buffers: pack into _back while the sender
        # pushes from its front buffer (lazy — the default streamed mode
        # packs in place and never needs the second copy of the weights)
        self._back: np.ndarray | None = None
        front = alloc_buffer(self.layout)
        if sender_groups > 1:
            # multi-NIC fan-out: one sender agent per interface (CIDR-picked
            # like the reference's 4-groups×8-engines layout,
            # fsdp_interface.py:97-138); the manager partitions the pool
            # across the advertised endpoints. ``advertise_host`` does not
            # apply here — each group advertises ITS OWN NIC's IP (use
            # sender_nic_cidr to steer which interfaces are picked).
            ips = pick_sender_ips(sender_groups, sender_nic_cidr)
            self.sender: SenderAgent | SenderGroup = SenderGroup(
                front, ips, manager_client=manager_client,
                num_streams=num_streams, poll_s=poll_s,
                cfg=self.cfg, fault=fault, layout=self.layout,
                trainer_spec=self.trainer_spec)
            endpoints = self.sender.endpoints
        else:
            self.sender = SenderAgent(front, manager_client=manager_client,
                                      num_streams=num_streams, poll_s=poll_s,
                                      advertise_host=advertise_host,
                                      cfg=self.cfg, fault=fault,
                                      layout=self.layout,
                                      trainer_spec=self.trainer_spec)
            endpoints = [self.sender.endpoint]
        self.manager = manager_client
        # async push state: pending pack/wire rounds CHAIN on a FIFO of
        # "weight-push" threads — each joins its predecessor before arming
        # the sender, so rounds serialize on the one buffer while the
        # foreground never blocks. _push_issued/_push_landed back the
        # pipelined trainer's bounded-staleness admission gate
        # (push_lag()/wait_push_lag(); ARCHITECTURE.md "Bounded-staleness
        # async training"): up to staleness_limit-1 rounds may be in
        # flight while generation streams against the last landed version.
        self._push_cv = threading.Condition()
        self._push_thread: threading.Thread | None = None
        self._push_err: BaseException | None = None
        self._push_issued = 0
        self._push_landed = 0
        self._last_async_version = -1
        self.sender.start()
        if manager_client is not None:
            manager_client.update_weight_senders(
                endpoints, groups_per_sender=groups_per_sender)

    def _pack_full(self, params: Any, buffer: np.ndarray) -> None:
        """Serial-mode pack. Mesh-sharded trainers go through the range
        path — ``pack_params_ranges`` reads each leaf's addressable shards
        (axis-0 block copies) instead of ``device_get`` on the global
        array, so no full-buffer gather materializes per leaf; replicated
        templates keep the batched ``pack_params`` fast path."""
        if self.trainer_spec is not None and self.trainer_spec.num_shards > 1:
            pack_params_ranges(params, self.layout, buffer,
                               [(0, self.layout.total_bytes)])
        else:
            pack_params(params, self.layout, buffer)

    def update_weights_with_agent(self, params: Any,
                                  streaming: bool = True) -> int:
        """Push new weights. Two modes:

        - ``streaming`` (default): version bump FIRST, then pack in place
          while sender streams trail the pack watermark — pack, wire, and
          (with a receiver-side ``on_tensor`` installer) the device upload
          all overlap inside the one round. This is what the <5 s
          trainer->rollout sync latency KPI measures (reference in-round
          pipeline: sender_agent.py:567-647).
        - serial: pack into the back buffer (overlapping any in-flight
          PREVIOUS round), then swap. Kept for multi-NIC sender groups
          (each group streams a different NIC; one shared watermark would
          serialize them on the slowest pack reader).

        Either way the manager version bump drains the active pool
        (fsdp_interface.py:80-95) and only re-activates instances that
        reach the CURRENT version, so a racing old-version push can never
        leave an instance serving stale weights.
        """
        t0 = time.monotonic()
        with obs.span("transfer/update_weights",
                      mb=round(self.layout.total_bytes / 1e6, 1)):
            version = self._update_weights_impl(params, streaming)
        # trainer-side pack+signal time; the wire time per instance is
        # observed sender-side as transfer/push_s (agents._push_one)
        obs.observe("transfer/pack_s", time.monotonic() - t0)
        return version

    def _update_weights_impl(self, params: Any, streaming: bool) -> int:
        t0 = time.monotonic()
        if streaming and isinstance(self.sender, SenderAgent):
            from .layout import pack_params_streaming
            from .tcp_engine import Watermark

            if self.manager is not None:
                version = self.manager.update_weight_version()
            else:
                version = self.sender.version + 1
            wm = Watermark(self.layout.total_bytes)
            # waits for in-flight rounds, then arms (buffer, version, wm)
            self.sender.signal_update_streaming(wm, version)
            try:
                pack_params_streaming(params, self.layout,
                                      self.sender.buffer, wm.advance)
            except BaseException as exc:
                wm.fail(str(exc))  # unblock gated streams -> round fails
                # and stop the poll loop from re-pushing the garbage round
                self.sender.mark_push_failed(version)
                raise
            wm.finish()
        else:
            if self._back is None:
                self._back = alloc_buffer(self.layout)
            self._pack_full(params, self._back)
            if self.manager is not None:
                version = self.manager.update_weight_version()
            else:
                version = self.sender.version + 1
            self._back = self.sender.swap_buffer(self._back, version)
        log.info("packed weights v%d (%.0f MB) in %.2fs", version,
                 self.layout.total_bytes / 1e6, time.monotonic() - t0)
        return version

    def update_weights_async(self, params: Any) -> int:
        """Non-blocking streamed push (the pipelined trainer's path): the
        manager version bump happens INLINE — it must drain the active pool
        before any instance could observe mixed versions, exactly like the
        sync path — and the pack/wire round (signal + streaming pack behind
        the watermark) completes on a background ``weight-push`` thread.
        Rounds QUEUE: a push issued while a previous round is still in
        flight chains behind it (the new thread joins its predecessor, and
        ``signal_update_streaming`` itself waits out the predecessor's wire
        before re-arming the buffer) — the foreground never blocks, which
        is what lets ``staleness_limit > 1`` overlap pushes with
        generation mid-stream. ``wait_pushed()`` drains the whole chain;
        ``wait_push_lag()`` is the bounded admission gate. Callers MUST
        pass host-resident arrays (the trainer snapshots via
        ``np.asarray`` first) so the background pack never touches a
        donated device buffer — with queued rounds each pending push holds
        its own host snapshot until it packs.

        Multi-NIC ``SenderGroup`` keeps its serial double-buffer round and
        degrades to the synchronous call (its pack already overlaps any
        in-flight previous round via the back buffer)."""
        if not isinstance(self.sender, SenderAgent):
            return self.update_weights_with_agent(params)
        if self.manager is not None:
            version = self.manager.update_weight_version()
        else:
            # managerless version issue must count QUEUED rounds too —
            # sender.version only advances when a round arms
            version = max(self.sender.version, self._last_async_version) + 1
        self._last_async_version = version
        ctx = obs.get_tracer().capture()
        t0 = time.monotonic()
        with self._push_cv:
            prev = self._push_thread
            self._push_issued += 1

        def _bg() -> None:
            if prev is not None:
                prev.join()
            try:
                with obs.get_tracer().adopt(ctx), \
                        obs.span("transfer/update_weights",
                                 mb=round(self.layout.total_bytes / 1e6, 1),
                                 mode="async"):
                    from .layout import pack_params_streaming
                    from .tcp_engine import Watermark

                    wm = Watermark(self.layout.total_bytes)
                    self.sender.signal_update_streaming(wm, version)
                    try:
                        pack_params_streaming(params, self.layout,
                                              self.sender.buffer, wm.advance)
                    except BaseException as exc:
                        wm.fail(str(exc))
                        self.sender.mark_push_failed(version)
                        raise
                    wm.finish()
                obs.observe("transfer/pack_s", time.monotonic() - t0)
                log.info("async-packed weights v%d (%.0f MB) in %.2fs",
                         version, self.layout.total_bytes / 1e6,
                         time.monotonic() - t0)
            except BaseException as exc:  # noqa: BLE001 — re-raised by fence
                with self._push_cv:
                    if self._push_err is None:
                        self._push_err = exc
            finally:
                # a failed round still LANDS (it is over): the lag gate
                # must unblock — the failure surfaces on the next fence
                with self._push_cv:
                    self._push_landed += 1
                    self._push_cv.notify_all()

        t = threading.Thread(target=_bg, name="weight-push", daemon=True)
        with self._push_cv:
            self._push_thread = t
        t.start()
        return version

    def push_lag(self) -> int:
        """Async push rounds issued but not yet landed (pack complete or
        failed). The pipelined trainer's bounded-staleness gauge feed."""
        with self._push_cv:
            return self._push_issued - self._push_landed

    def wait_push_lag(self, max_lag: int, timeout: float = 600.0) -> None:
        """Bounded-staleness admission gate: block until at most
        ``max_lag`` async push rounds are still in flight (``max_lag=0``
        ≡ the full ``wait_pushed`` fence), re-raising any background push
        failure. The pipeline calls this with ``staleness_limit - 1``
        before each prefetched stream's first request."""
        deadline = time.monotonic() + timeout
        with self._push_cv:
            while (self._push_issued - self._push_landed > max_lag
                   and self._push_err is None):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"weight-push lag still > {max_lag} after "
                        f"{timeout:.0f}s")
                self._push_cv.wait(remaining)
            err, self._push_err = self._push_err, None
        if err is not None:
            raise RuntimeError("async weight push failed") from err

    def wait_pushed(self, timeout: float = 600.0) -> None:
        """Fence on the async push chain: returns once every queued round's
        pack has fully landed (the point the SYNC path returns at —
        receivers version-gate behind the manager, so instance
        re-activation needs no trainer-side wait), re-raising any
        background failure."""
        with self._push_cv:
            t = self._push_thread
        if t is not None:
            # the newest thread joins its whole predecessor chain first,
            # so joining it alone drains every queued round
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError(
                    f"async weight push still running after {timeout:.0f}s")
            with self._push_cv:
                if self._push_thread is t:
                    self._push_thread = None
        with self._push_cv:
            err, self._push_err = self._push_err, None
        if err is not None:
            raise RuntimeError("async weight push failed") from err

    def set_laggard_callback(self, cb) -> None:
        """Wire the retry-budget-exhaustion escalation: ``cb(instance,
        reason)`` — train.py passes ``PoolManager.escalate_laggard`` so a
        dead receiver is drained + deregistered instead of re-pushed
        every poll forever."""
        self.sender.laggard_cb = cb

    def counters(self) -> dict[str, float]:
        """Cumulative ``transfer/*`` supervision gauges + config echo for
        step records (RemoteRollout.fault_counters merges these, so they
        ride every step record and the FlightRecorder's
        ``transfer/push_failures`` watch)."""
        out = dict(self.sender.counters())
        out["transfer/min_bandwidth_mbps"] = float(
            self.cfg.min_bandwidth_mbps)
        out["transfer/retry_budget"] = float(self.cfg.retry_budget)
        if self.fault is not None:
            out.update(self.fault.counters())
        return out

    def sync_health(self) -> dict[str, dict]:
        """Per-instance push health (``PoolManager.transfer_health_fn``
        feeds the /statusz pool section's per-engine ``transfer`` block)."""
        return self.sender.sync_health()

    def close(self) -> None:
        try:
            # a push mid-flight holds the sender's buffer/round state;
            # give it a bounded window before tearing the agent down
            self.wait_pushed(timeout=30.0)
        except Exception:  # noqa: BLE001 — teardown must proceed
            log.exception("async weight push failed during close")
        # SenderAgent.stop shuts the push/notify executors down with
        # cancel_futures and joins the accept/event threads, so a teardown
        # mid-push cannot leak threads past the conftest guard
        self.sender.stop()


def colocated_update(engine, params: Any, version: int | None = None) -> None:
    """In-process hand-off to a colocated rollout engine (device_put with the
    engine's shardings — SURVEY §2.2: 'TP broadcast disappears into GSPMD')."""
    engine.update_weights(params, version=version)
