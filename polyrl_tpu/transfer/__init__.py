"""Weight-transfer fabric: trainer -> rollout weight sync.

Layers (SURVEY §3.3):
- ``layout``     — flat name->(shape,dtype,offset) buffer layout
- ``tcp_engine`` — multi-stream TCP bulk transfer (cross-host / DCN)
- ``agents``     — sender (trainer side) / receiver (rollout side) with a
                   single JSON-over-TCP control channel
- ``interface``  — trainer facade (pack + version + signal); colocated path
                   is a ``device_put`` reshard
"""

from .agents import ReceiverAgent, SenderAgent
from .interface import TransferInterface, colocated_update
from .layout import (
    ParamLayout,
    alloc_buffer,
    build_layout,
    pack_params,
    unflatten_like,
    unpack_params,
)
from .tcp_engine import TcpTransferEngine

__all__ = [
    "ParamLayout",
    "ReceiverAgent",
    "SenderAgent",
    "TcpTransferEngine",
    "TransferInterface",
    "alloc_buffer",
    "build_layout",
    "colocated_update",
    "pack_params",
    "unflatten_like",
    "unpack_params",
]
