"""Weight-transfer fabric: trainer -> rollout weight sync.

Layers (SURVEY §3.3):
- ``layout``     — flat name->(shape,dtype,offset) buffer layout
- ``tcp_engine`` — multi-stream TCP bulk transfer (cross-host / DCN)
- ``agents``     — sender (trainer side) / receiver (rollout side) with a
                   single JSON-over-TCP control channel
- ``interface``  — trainer facade (pack + version + signal); colocated path
                   is a ``device_put`` reshard
"""

from .agents import ReceiverAgent, SenderAgent, SenderGroup, TransferConfig
from .interface import TransferInterface, colocated_update
from .nic import filter_ips_by_cidr, get_node_ips, pick_sender_ips
from .layout import (
    ParamLayout,
    alloc_buffer,
    build_layout,
    pack_params,
    unflatten_like,
    unpack_params,
)
from .tcp_engine import TcpTransferEngine

__all__ = [
    "ParamLayout",
    "ReceiverAgent",
    "SenderAgent",
    "SenderGroup",
    "TcpTransferEngine",
    "TransferConfig",
    "TransferInterface",
    "alloc_buffer",
    "build_layout",
    "colocated_update",
    "filter_ips_by_cidr",
    "get_node_ips",
    "pack_params",
    "pick_sender_ips",
    "unflatten_like",
    "unpack_params",
]
