"""Multi-stream TCP bulk transfer engine.

TPU-native counterpart of the reference's TCPTransferEngine
(rlboost/weight_transfer/transfer_engine.py:14-274): N parallel TCP streams
per transfer, 16-byte (offset, length) header per stream, receiver
``recv_into`` directly into a registered buffer memoryview (zero-copy), and
an async submit/poll API. Hardware-agnostic — this is the cross-host (DCN)
path; in-slice weight movement uses ``jax.device_put`` resharding instead.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field

SOCK_BUF = 16 * 1024 * 1024  # 16 MB socket buffers (transfer_engine.py:40-42)
SEND_CHUNK = 64 * 1024 * 1024  # 64 MB send chunks
# streamed (watermark) mode: round-robin stripe per stream — small enough
# that every stream's next needed byte stays within n_streams*STRIPE of the
# packer (all streams active the whole round), big enough to amortize frames
STREAM_STRIPE = 16 * 1024 * 1024
HEADER = struct.Struct("<QQQQ")  # (round_id, offset, length, total_streams)


def _tune(sock: socket.socket) -> None:
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, SOCK_BUF)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, SOCK_BUF)
    except OSError:
        pass


class Watermark:
    """Progress gate for streaming a buffer that is still being packed.

    The packer advances the high-water mark as bytes [0, value) become
    valid; sender streams block before sending past it. This is what
    overlaps pack -> wire -> install inside ONE push round (the reference's
    sender pipeline, sender_agent.py:567-647) — the double-buffer only
    overlaps a pack with the PREVIOUS round."""

    def __init__(self, total: int):
        self.total = int(total)
        self._value = 0
        self._failed: str | None = None
        self._cv = threading.Condition()

    @property
    def value(self) -> int:
        with self._cv:
            return self._value

    def advance(self, new_value: int) -> None:
        with self._cv:
            if new_value > self._value:
                self._value = new_value
                self._cv.notify_all()

    def finish(self) -> None:
        self.advance(self.total)

    def fail(self, msg: str) -> None:
        with self._cv:
            self._failed = msg or "pack failed"
            self._cv.notify_all()

    def wait_until(self, target: int, timeout: float = 3600.0) -> None:
        # default budget matches the sender's stream_push_timeout_s: the
        # gate spans pack progress, which shares the combined round clock
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._value < target and self._failed is None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"watermark stalled at {self._value}/{target}")
                self._cv.wait(min(left, 1.0))
            if self._failed is not None:
                raise ConnectionError(f"streamed pack failed: {self._failed}")


def split_ranges(total: int, n: int) -> list[tuple[int, int]]:
    """Split [0, total) into <=n contiguous (offset, length) ranges."""
    n = max(1, min(n, total)) if total else 1
    base, rem = divmod(total, n)
    out, off = [], 0
    for i in range(n):
        ln = base + (1 if i < rem else 0)
        if ln:
            out.append((off, ln))
        off += ln
    return out


class ReceiverSockets:
    """N listener sockets writing incoming streams straight into a buffer.

    Accept loops are persistent (one thread per listener, started once):
    each transfer round carries a round_id in the stream header, and
    connections from an aborted earlier round are rejected by id — so a
    failed round can never corrupt the accounting of the next one.
    """

    def __init__(self, buffer, num_streams: int, host: str = "0.0.0.0"):
        self._mv = memoryview(buffer).cast("B")
        self._socks: list[socket.socket] = []
        self._done = threading.Event()
        self._errors: list[str] = []
        self._completed = 0
        self._expected: int | None = None
        self._round = -1
        self._progress: dict[int, int] = {}  # range offset -> bytes landed
        self._conns: dict[int, list] = {}  # round -> live data connections
        self._lock = threading.Lock()
        self._closed = False
        self.ports: list[int] = []
        for _ in range(num_streams):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            _tune(s)
            s.bind((host, 0))
            s.listen(4)
            self._socks.append(s)
            self.ports.append(s.getsockname()[1])
        self._threads = [
            threading.Thread(target=self._serve_loop, args=(s,), daemon=True)
            for s in self._socks
        ]
        for t in self._threads:
            t.start()

    def arm(self, round_id: int) -> None:
        """Begin accepting one transfer round tagged ``round_id``."""
        with self._lock:
            self._round = round_id
            self._completed = 0
            self._expected: int | None = None
            self._progress = {}
            self._errors.clear()
            self._done.clear()
            # force-close dangling streams from older rounds: their header
            # passed the round check back then, so their recv loops would
            # keep writing stale bytes into the buffer UNDER the new round
            stale = [c for r, conns in self._conns.items()
                     if r != round_id for c in conns]
            self._conns = {round_id: self._conns.get(round_id, [])}
        for c in stale:
            try:
                # shutdown (NOT close) wakes a recv_into blocked in the
                # kernel; the owning serve thread's `with conn:` does the
                # close — closing here would free the fd number for a new
                # accept while the serve thread could still recv on it
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def _serve_loop(self, listener: socket.socket) -> None:
        while not self._closed:
            try:
                conn, _ = listener.accept()
            except OSError:
                return  # closed
            round_id = None
            try:
                with conn:
                    _tune(conn)
                    hdr = self._recv_header(conn, first=True)
                    if hdr is None:
                        raise ConnectionError("eof in header")
                    round_id, offset, length, nstreams = hdr
                    with self._lock:
                        if round_id != self._round:
                            continue  # stale stream from an aborted round
                        self._expected = nstreams
                        self._conns.setdefault(round_id, []).append(conn)
                    # a stream is a SEQUENCE of (offset, length) framed
                    # ranges (streamed mode interleaves round-robin stripes
                    # so every stream trails the packer; serial mode sends
                    # exactly one contiguous range). Clean EOF at a frame
                    # boundary terminates the stream.
                    while True:
                        view = self._mv[offset : offset + length]
                        got = 0
                        while got < length:
                            n = conn.recv_into(view[got:],
                                               min(length - got, SOCK_BUF))
                            if n == 0:
                                raise ConnectionError(
                                    f"eof at {got}/{length}")
                            got += n
                            with self._lock:
                                if round_id == self._round:
                                    self._progress[offset] = got
                        hdr = self._recv_header(conn, first=False)
                        if hdr is None:
                            break  # clean EOF: stream complete
                        r2, offset, length, _ = hdr
                        if r2 != round_id:
                            raise ConnectionError(
                                "round id changed mid-stream")
                        if length == 0:
                            break
                    with self._lock:
                        if round_id != self._round:
                            continue
                        self._completed += 1
                        if self._completed == self._expected:
                            self._done.set()
            except Exception as exc:  # noqa: BLE001 — reported to waiter
                with self._lock:
                    # only fail the round this stream belongs to — a dangling
                    # connection from an aborted round must not poison the
                    # retry's accounting
                    if round_id == self._round:
                        self._errors.append(str(exc))
                        self._done.set()

    @staticmethod
    def _recv_header(conn: socket.socket, first: bool):
        """Read one frame header; None on clean EOF at the boundary (only
        legal between frames — ``first=True`` treats it as an error)."""
        hdr = b""
        while len(hdr) < HEADER.size:
            chunk = conn.recv(HEADER.size - len(hdr))
            if not chunk:
                if hdr or first:
                    raise ConnectionError(
                        f"eof mid-header ({len(hdr)}/{HEADER.size})")
                return None
            hdr += chunk
        return HEADER.unpack(hdr)

    def coverage(self) -> list[tuple[int, int]]:
        """Snapshot of (range_offset, bytes_landed) for the armed round —
        the receive-side watermark an incremental installer polls."""
        with self._lock:
            return sorted(self._progress.items())

    def wait(self, timeout: float | None = None) -> None:
        if not self._done.wait(timeout):
            raise TimeoutError("transfer receive timed out")
        with self._lock:
            if self._errors:
                raise ConnectionError("; ".join(self._errors))

    def close(self) -> None:
        self._closed = True
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass


@dataclass
class TransferBatch:
    futures: list[Future] = field(default_factory=list)

    def done(self) -> bool:
        return all(f.done() for f in self.futures)

    def result(self, timeout: float | None = None) -> None:
        for f in self.futures:
            f.result(timeout)


class TcpTransferEngine:
    """Sender side: fan a buffer out over N parallel streams.

    ``bind_host`` pins the outbound streams' SOURCE address to one local
    interface — multi-NIC hosts run one engine per NIC so sender groups
    aggregate bandwidth instead of sharing the default route (reference
    per-group local_hostname, fsdp_interface.py:118-126)."""

    def __init__(self, num_streams: int = 8, workers: int | None = None,
                 bind_host: str | None = None):
        self.num_streams = num_streams
        self.bind_host = bind_host
        self._pool = ThreadPoolExecutor(max_workers=workers or num_streams)

    def _send_ranges(self, host: str, port: int, mv: memoryview,
                     round_id: int, ranges: list[tuple[int, int]],
                     nstreams: int,
                     watermark: "Watermark | None" = None) -> None:
        """One stream = one connection carrying a sequence of framed
        (offset, length) ranges; closing the connection at a frame boundary
        terminates the stream (ReceiverSockets._serve_loop)."""
        src = (self.bind_host, 0) if self.bind_host else None
        # smaller chunks under a watermark: the gate advances per packed
        # tensor group, and a 64 MB chunk would add that much latency to
        # every gate crossing
        chunk = SEND_CHUNK if watermark is None else SOCK_BUF
        with socket.create_connection((host, port), timeout=60.0,
                                      source_address=src) as s:
            _tune(s)
            for offset, length in ranges:
                s.sendall(HEADER.pack(round_id, offset, length, nstreams))
                end = offset + length
                pos = offset
                while pos < end:
                    nxt = min(pos + chunk, end)
                    if watermark is not None:
                        watermark.wait_until(nxt)
                    s.sendall(mv[pos:nxt])
                    pos = nxt

    def transfer_submit_write(self, host: str, ports: list[int], buffer,
                              round_id: int = 0,
                              watermark: "Watermark | None" = None,
                              ) -> TransferBatch:
        """Split ``buffer`` across ``ports`` and send concurrently.

        Serial mode: one contiguous range per stream (bandwidth-optimal for
        an already-packed buffer). Streamed (``watermark``) mode: STRIPE
        chunks assigned round-robin, so every stream works just behind the
        packer — contiguous ranges would leave stream k idle until the
        watermark crossed its start offset, serializing the round's wire
        behind pack order (advisor r4)."""
        mv = memoryview(buffer).cast("B")
        batch = TransferBatch()
        if watermark is None:
            assignments = [[r] for r in split_ranges(len(mv), len(ports))]
        else:
            total = len(mv)
            chunks = [(off, min(STREAM_STRIPE, total - off))
                      for off in range(0, total, STREAM_STRIPE)]
            n_active = min(len(ports), len(chunks)) or 1
            assignments = [c for c in
                           (chunks[i::n_active] for i in range(n_active))
                           if c]
        for ranges, port in zip(assignments, ports):
            batch.futures.append(self._pool.submit(
                self._send_ranges, host, port, mv, round_id, ranges,
                len(assignments), watermark))
        return batch

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
