"""Multi-stream TCP bulk transfer engine.

TPU-native counterpart of the reference's TCPTransferEngine
(rlboost/weight_transfer/transfer_engine.py:14-274): N parallel TCP streams
per transfer, 16-byte (offset, length) header per stream, receiver
``recv_into`` directly into a registered buffer memoryview (zero-copy), and
an async submit/poll API. Hardware-agnostic — this is the cross-host (DCN)
path; in-slice weight movement uses ``jax.device_put`` resharding instead.

Integrity (ARCHITECTURE.md "Weight-fabric fault tolerance"): every frame's
payload is followed by a 4-byte CRC32 trailer computed over the TRUE source
bytes. The receiver verifies it incrementally as bytes land; a mismatching
frame is rejected — its bytes are dropped from the coverage ledger so the
round's control-channel verify step demands a re-push of exactly that
range. ``transfer_submit_write`` returns the per-frame (offset, length,
crc) manifest through ``TransferBatch.result`` so the sender can ship it
on the control channel for the receiver's authoritative whole-round check.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field

SOCK_BUF = 16 * 1024 * 1024  # 16 MB socket buffers (transfer_engine.py:40-42)
SEND_CHUNK = 64 * 1024 * 1024  # 64 MB send chunks
# streamed (watermark) mode: round-robin stripe per stream — small enough
# that every stream's next needed byte stays within n_streams*STRIPE of the
# packer (all streams active the whole round), big enough to amortize frames
STREAM_STRIPE = 16 * 1024 * 1024
HEADER = struct.Struct("<QQQQ")  # (round_id, offset, length, total_streams)
FOOTER = struct.Struct("<I")     # per-frame payload CRC32 trailer


def _tune(sock: socket.socket) -> None:
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, SOCK_BUF)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, SOCK_BUF)
    except OSError:
        pass


class Watermark:
    """Progress gate for streaming a buffer that is still being packed.

    The packer advances the high-water mark as bytes [0, value) become
    valid; sender streams block before sending past it. This is what
    overlaps pack -> wire -> install inside ONE push round (the reference's
    sender pipeline, sender_agent.py:567-647) — the double-buffer only
    overlaps a pack with the PREVIOUS round."""

    def __init__(self, total: int):
        self.total = int(total)
        self._value = 0
        self._failed: str | None = None
        self._cv = threading.Condition()

    @property
    def value(self) -> int:
        with self._cv:
            return self._value

    def advance(self, new_value: int) -> None:
        with self._cv:
            if new_value > self._value:
                self._value = new_value
                self._cv.notify_all()

    def finish(self) -> None:
        self.advance(self.total)

    def fail(self, msg: str) -> None:
        with self._cv:
            self._failed = msg or "pack failed"
            self._cv.notify_all()

    def wait_until(self, target: int, timeout: float = 3600.0) -> None:
        # default budget matches the sender's streamed-round cap; callers
        # with a bandwidth-keyed round deadline pass it through so a dead
        # pack can never pin a sender thread for the full hour
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._value < target and self._failed is None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"watermark stalled at {self._value}/{target}")
                self._cv.wait(min(left, 1.0))
            if self._failed is not None:
                raise ConnectionError(f"streamed pack failed: {self._failed}")


def split_ranges(total: int, n: int) -> list[tuple[int, int]]:
    """Split [0, total) into <=n contiguous (offset, length) ranges."""
    n = max(1, min(n, total)) if total else 1
    base, rem = divmod(total, n)
    out, off = [], 0
    for i in range(n):
        ln = base + (1 if i < rem else 0)
        if ln:
            out.append((off, ln))
        off += ln
    return out


class ReceiverSockets:
    """N listener sockets writing incoming streams straight into a buffer.

    Accept loops are persistent (one thread per listener, started once):
    each transfer round carries a round_id in the stream header, and
    connections from an aborted earlier round are rejected by id — so a
    failed round can never corrupt the accounting of the next one.
    """

    def __init__(self, buffer, num_streams: int, host: str = "0.0.0.0"):
        self._mv = memoryview(buffer).cast("B")
        self._socks: list[socket.socket] = []
        self._done = threading.Event()
        self._errors: list[str] = []
        self._completed = 0
        self._expected: int | None = None
        self._round = -1
        self._progress: dict[int, int] = {}  # range offset -> bytes landed
        self._conns: dict[int, list] = {}  # round -> live data connections
        self._lock = threading.Lock()
        self._closed = False
        # integrity ledger: frames whose CRC32 trailer mismatched are
        # rejected (their bytes dropped from the coverage so the round's
        # verify step demands a re-push); cumulative counter for telemetry
        self.crc_failures = 0
        self._resume = False  # current round re-pushes ranges of the prior
        self.ports: list[int] = []
        for _ in range(num_streams):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            _tune(s)
            s.bind((host, 0))
            s.listen(4)
            self._socks.append(s)
            self.ports.append(s.getsockname()[1])
        self._threads = [
            threading.Thread(target=self._serve_loop, args=(s,), daemon=True)
            for s in self._socks
        ]
        for t in self._threads:
            t.start()

    def arm(self, round_id: int, reset: bool = True,
            clear: list[tuple[int, int]] | None = None) -> None:
        """Begin accepting one transfer round tagged ``round_id``.

        ``reset=False`` arms a RESUME round: the coverage ledger of the
        superseded round is kept (its landed, CRC-verified bytes stay
        valid — same version, byte-identical source) and only the
        ``clear`` ranges about to be re-pushed are dropped, so a partial
        re-push completes the round instead of restarting it."""
        with self._lock:
            self._round = round_id
            self._completed = 0
            self._expected: int | None = None
            if reset:
                self._progress = {}
            elif clear:
                for off, _length in clear:
                    self._progress.pop(int(off), None)
            self._resume = not reset
            self._errors.clear()
            self._done.clear()
            # force-close dangling streams from older rounds: their header
            # passed the round check back then, so their recv loops would
            # keep writing stale bytes into the buffer UNDER the new round
            stale = [c for r, conns in self._conns.items()
                     if r != round_id for c in conns]
            self._conns = {round_id: self._conns.get(round_id, [])}
        for c in stale:
            try:
                # shutdown (NOT close) wakes a recv_into blocked in the
                # kernel; the owning serve thread's `with conn:` does the
                # close — closing here would free the fd number for a new
                # accept while the serve thread could still recv on it
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def _serve_loop(self, listener: socket.socket) -> None:
        while not self._closed:
            try:
                conn, _ = listener.accept()
            except OSError:
                return  # closed
            round_id = None
            try:
                with conn:
                    _tune(conn)
                    hdr = self._recv_header(conn, first=True)
                    if hdr is None:
                        raise ConnectionError("eof in header")
                    round_id, offset, length, nstreams = hdr
                    with self._lock:
                        if round_id != self._round:
                            continue  # stale stream from an aborted round
                        self._expected = nstreams
                        self._conns.setdefault(round_id, []).append(conn)
                    # a stream is a SEQUENCE of (offset, length) framed
                    # ranges (streamed mode interleaves round-robin stripes
                    # so every stream trails the packer; serial mode sends
                    # exactly one contiguous range). Clean EOF at a frame
                    # boundary terminates the stream.
                    while True:
                        view = self._mv[offset : offset + length]
                        got = 0
                        crc = 0
                        while got < length:
                            n = conn.recv_into(view[got:],
                                               min(length - got, SOCK_BUF))
                            if n == 0:
                                raise ConnectionError(
                                    f"eof at {got}/{length}")
                            crc = zlib.crc32(view[got:got + n], crc)
                            got += n
                            with self._lock:
                                if round_id == self._round:
                                    self._progress[offset] = got
                        want = FOOTER.unpack(
                            self._recv_exact(conn, FOOTER.size))[0]
                        if want != crc:
                            # integrity: reject the frame — its bytes are
                            # dropped from the coverage ledger so the
                            # verify step demands a re-push of exactly
                            # this range. The stream itself stays healthy
                            # (framing is intact), so later frames land.
                            with self._lock:
                                if round_id == self._round:
                                    self.crc_failures += 1
                                    self._progress.pop(offset, None)
                        hdr = self._recv_header(conn, first=False)
                        if hdr is None:
                            break  # clean EOF: stream complete
                        r2, offset, length, _ = hdr
                        if r2 != round_id:
                            raise ConnectionError(
                                "round id changed mid-stream")
                        if length == 0:
                            break
                    with self._lock:
                        if round_id != self._round:
                            continue
                        self._completed += 1
                        if self._completed == self._expected:
                            self._done.set()
            except Exception as exc:  # noqa: BLE001 — reported to waiter
                with self._lock:
                    # only fail the round this stream belongs to — a dangling
                    # connection from an aborted round must not poison the
                    # retry's accounting
                    if round_id == self._round:
                        self._errors.append(str(exc))
                        self._done.set()

    @staticmethod
    def _recv_header(conn: socket.socket, first: bool):
        """Read one frame header; None on clean EOF at the boundary (only
        legal between frames — ``first=True`` treats it as an error)."""
        hdr = b""
        while len(hdr) < HEADER.size:
            chunk = conn.recv(HEADER.size - len(hdr))
            if not chunk:
                if hdr or first:
                    raise ConnectionError(
                        f"eof mid-header ({len(hdr)}/{HEADER.size})")
                return None
            hdr += chunk
        return HEADER.unpack(hdr)

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError(
                    f"eof mid-frame-trailer ({len(buf)}/{n})")
            buf += chunk
        return buf

    def coverage(self) -> list[tuple[int, int]]:
        """Snapshot of (range_offset, bytes_landed) for the armed round —
        the receive-side watermark an incremental installer polls."""
        with self._lock:
            return sorted(self._progress.items())

    def _merged(self) -> list[list[int]]:
        """Merged [lo, hi) covered intervals (caller holds ``_lock``)."""
        merged: list[list[int]] = []
        for off, got in sorted(self._progress.items()):
            if got <= 0:
                continue
            if merged and off <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], off + got)
            else:
                merged.append([off, off + got])
        return merged

    def gaps(self, total: int) -> list[tuple[int, int]]:
        """Uncovered (offset, length) holes of [0, total) in the armed
        round's ledger — what a partial re-push must still deliver."""
        with self._lock:
            merged = self._merged()
        out: list[tuple[int, int]] = []
        pos = 0
        for lo, hi in merged:
            if lo > pos:
                out.append((pos, lo - pos))
            pos = max(pos, hi)
        if pos < total:
            out.append((pos, total - pos))
        return out

    def verify_ranges(self, manifest) -> list[tuple[int, int]]:
        """Manifest entries ``(offset, length, crc32)`` that did NOT land
        intact: not fully covered by the ledger, or the buffer bytes'
        recomputed CRC mismatches the sender's digest. This is the
        receiver's authoritative whole-round check — the per-frame trailer
        already rejected corrupt frames at land time; this re-derivation
        from the buffer catches anything that slipped past it (torn
        writes, a stale stream, a frame the trailer happened to match)."""
        with self._lock:
            merged = self._merged()
        bad: list[tuple[int, int]] = []
        for off, length, want in manifest:
            off, length, want = int(off), int(length), int(want)
            covered = any(lo <= off and off + length <= hi
                          for lo, hi in merged)
            if not covered or zlib.crc32(
                    self._mv[off:off + length]) != want:
                bad.append((off, length))
        return bad

    @property
    def resume_round(self) -> bool:
        """True while the armed round is a partial re-push."""
        with self._lock:
            return self._resume

    def wait_done(self, timeout: float | None = None) -> bool:
        """Non-raising completion wait: True once every expected stream of
        the armed round terminated (cleanly or with an error). The verify
        step reads the ledger either way — a dead stream is just a gap."""
        return self._done.wait(timeout)

    def wait(self, timeout: float | None = None) -> None:
        if not self._done.wait(timeout):
            raise TimeoutError("transfer receive timed out")
        with self._lock:
            if self._errors:
                raise ConnectionError("; ".join(self._errors))

    def close(self) -> None:
        self._closed = True
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass


@dataclass
class TransferBatch:
    futures: list[Future] = field(default_factory=list)
    # per-stream (offset, length) lists, index-aligned with ``futures`` —
    # the sharded push reads these to scope a failed stream's re-push to
    # exactly the ranges that stream owned
    assignments: list[list[tuple[int, int]]] = field(default_factory=list)

    def done(self) -> bool:
        return all(f.done() for f in self.futures)

    def result(self, timeout: float | None = None) -> list[tuple[int, int, int]]:
        """Wait for every stream; returns the round's frame manifest —
        ``(offset, length, crc32)`` per frame actually sent — which the
        sender ships on the control channel for receiver-side verify."""
        manifest: list[tuple[int, int, int]] = []
        for f in self.futures:
            manifest.extend(f.result(timeout) or [])
        return manifest


class TcpTransferEngine:
    """Sender side: fan a buffer out over N parallel streams.

    ``bind_host`` pins the outbound streams' SOURCE address to one local
    interface — multi-NIC hosts run one engine per NIC so sender groups
    aggregate bandwidth instead of sharing the default route (reference
    per-group local_hostname, fsdp_interface.py:118-126)."""

    def __init__(self, num_streams: int = 8, workers: int | None = None,
                 bind_host: str | None = None):
        self.num_streams = num_streams
        self.bind_host = bind_host
        self._pool = ThreadPoolExecutor(max_workers=workers or num_streams)

    def _send_ranges(self, host: str, port: int, mv: memoryview,
                     round_id: int, ranges: list[tuple[int, int]],
                     nstreams: int,
                     watermark: "Watermark | None" = None,
                     gate_timeout_s: float | None = None,
                     fault=None, instance: str = "",
                     stream_idx: int = 0) -> list[tuple[int, int, int]]:
        """One stream = one connection carrying a sequence of framed
        (offset, length) ranges; closing the connection at a frame boundary
        terminates the stream (ReceiverSockets._serve_loop). Each frame's
        payload is followed by a CRC32 trailer over the TRUE source bytes
        (computed before any injected wire corruption, so a corrupted
        payload is detectable). Returns this stream's frame manifest."""
        src = (self.bind_host, 0) if self.bind_host else None
        # smaller chunks under a watermark: the gate advances per packed
        # tensor group, and a 64 MB chunk would add that much latency to
        # every gate crossing
        chunk = SEND_CHUNK if watermark is None else SOCK_BUF
        manifest: list[tuple[int, int, int]] = []
        with socket.create_connection((host, port), timeout=60.0,
                                      source_address=src) as s:
            _tune(s)
            if fault is not None:
                # transfer-plane chaos: a stalled stream blows the round
                # past its bandwidth-keyed deadline (rollout/faults.py)
                fault.maybe_stall(instance, stream_idx)
            for offset, length in ranges:
                s.sendall(HEADER.pack(round_id, offset, length, nstreams))
                corrupt = (fault is not None
                           and fault.take_corruption(instance, stream_idx))
                end = offset + length
                pos = offset
                crc = 0
                while pos < end:
                    nxt = min(pos + chunk, end)
                    if watermark is not None:
                        watermark.wait_until(
                            nxt, timeout=gate_timeout_s or 3600.0)
                    payload = mv[pos:nxt]
                    crc = zlib.crc32(payload, crc)  # TRUE bytes, pre-fault
                    if corrupt:
                        bad = bytearray(payload)
                        bad[0] ^= 0xFF
                        payload = bytes(bad)
                        corrupt = False  # one flipped chunk is enough
                    s.sendall(payload)
                    pos = nxt
                s.sendall(FOOTER.pack(crc))
                manifest.append((offset, length, crc))
        return manifest

    def transfer_submit_write(self, host: str, ports: list[int], buffer,
                              round_id: int = 0,
                              watermark: "Watermark | None" = None,
                              ranges: list[tuple[int, int]] | None = None,
                              gate_timeout_s: float | None = None,
                              fault=None, instance: str = "",
                              assignments: list[list[tuple[int, int]]]
                              | None = None,
                              ) -> TransferBatch:
        """Split ``buffer`` across ``ports`` and send concurrently.

        Serial mode: one contiguous range per stream (bandwidth-optimal for
        an already-packed buffer). Streamed (``watermark``) mode: STRIPE
        chunks assigned round-robin, so every stream works just behind the
        packer — contiguous ranges would leave stream k idle until the
        watermark crossed its start offset, serializing the round's wire
        behind pack order (advisor r4). Explicit ``ranges`` is the RESUME
        path: only the given (offset, length) ranges are sent, assigned
        round-robin across the streams — a post-``verify_failed`` re-push
        delivers the failed ranges without restarting the round. Explicit
        ``assignments`` is the SHARDED path (transfer/layout.py
        ReshardingMap.stream_assignments): stream i carries exactly
        ``assignments[i]`` — the caller owns the balance/affinity."""
        mv = memoryview(buffer).cast("B")
        batch = TransferBatch()
        if assignments is not None:
            assignments = [[(int(o), int(ln)) for o, ln in rs if int(ln) > 0]
                           for rs in assignments]
            assignments = [rs for rs in assignments if rs]
            if not assignments:
                assignments = [[(0, 0)]]
        elif ranges is not None:
            rs = [(int(o), int(ln)) for o, ln in ranges if int(ln) > 0]
            n_active = min(len(ports), len(rs)) or 1
            assignments = [c for c in
                           (rs[i::n_active] for i in range(n_active)) if c]
            if not assignments:
                assignments = [[(0, 0)]] if not rs else assignments
        elif watermark is None:
            assignments = [[r] for r in split_ranges(len(mv), len(ports))]
        else:
            total = len(mv)
            chunks = [(off, min(STREAM_STRIPE, total - off))
                      for off in range(0, total, STREAM_STRIPE)]
            n_active = min(len(ports), len(chunks)) or 1
            assignments = [c for c in
                           (chunks[i::n_active] for i in range(n_active))
                           if c]
        for i, (rngs, port) in enumerate(zip(assignments, ports)):
            batch.assignments.append(list(rngs))
            batch.futures.append(self._pool.submit(
                self._send_ranges, host, port, mv, round_id, rngs,
                len(assignments), watermark, gate_timeout_s, fault,
                instance, i))
        return batch

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
