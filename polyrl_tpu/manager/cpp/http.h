// Minimal HTTP/1.1 server + client over POSIX sockets.
//
// Control-plane scale (tens of rollout instances, one trainer): a
// thread-per-connection blocking server is simpler and plenty — the data
// plane's heavy lifting (token streaming) is line-oriented proxying, which
// the client here exposes as a streaming line reader. Plays the role of
// axum/reqwest in the reference manager (SURVEY.md C16, main.rs:56-70).
#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstring>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "pool.h"

namespace phttp {

struct Request {
  std::string method;
  std::string path;     // without query
  std::string query;
  std::map<std::string, std::string> headers;
  std::string body;
  std::string peer_ip;  // dotted-quad of the connecting socket (for ACLs)
};

// Streaming response writer handed to handlers. Either set status+body and
// return, or call start_stream() then write_chunk() for chunked NDJSON.
class ResponseWriter {
 public:
  explicit ResponseWriter(int fd) : fd_(fd) {}

  int status = 200;
  std::string content_type = "application/json";
  std::string body;
  // extra response headers, each a full "Name: value\r\n" line (e.g. the
  // X-Trace-Id echo); emitted by both the plain and the streaming path
  std::string extra_headers;

  bool start_stream() {
    if (streaming_) return true;
    std::string head = "HTTP/1.1 200 OK\r\nContent-Type: " + content_type +
                       "\r\nTransfer-Encoding: chunked\r\nConnection: close\r\n" +
                       extra_headers + "\r\n";
    if (!write_all(head.data(), head.size())) return false;
    streaming_ = true;
    return true;
  }

  bool write_chunk(const std::string& data) {
    if (data.empty()) return true;
    char len[32];
    snprintf(len, sizeof(len), "%zx\r\n", data.size());
    std::string chunk = std::string(len) + data + "\r\n";
    return write_all(chunk.data(), chunk.size());
  }

  void finish() {
    if (streaming_) {
      const char* end = "0\r\n\r\n";
      write_all(end, 5);
    } else {
      char head[256];
      const char* status_text = status == 200 ? "OK" : (status == 404 ? "Not Found" : (status == 403 ? "Forbidden" : (status >= 500 ? "Internal Server Error" : "Bad Request")));
      snprintf(head, sizeof(head),
               "HTTP/1.1 %d %s\r\nContent-Type: %s\r\nContent-Length: %zu\r\nConnection: close\r\n",
               status, status_text, content_type.c_str(), body.size());
      std::string full = std::string(head) + extra_headers + "\r\n";
      write_all(full.data(), full.size());
      write_all(body.data(), body.size());
    }
  }

  bool streaming() const { return streaming_; }

 private:
  bool write_all(const char* data, size_t len) {
    size_t off = 0;
    while (off < len) {
      ssize_t n = ::send(fd_, data + off, len - off, MSG_NOSIGNAL);
      if (n <= 0) return false;
      off += static_cast<size_t>(n);
    }
    return true;
  }

  int fd_;
  bool streaming_ = false;
};

using Handler = std::function<void(const Request&, ResponseWriter&)>;

class Server {
 public:
  // Bounded connection concurrency (round-1 finding: thread-per-connection
  // was unbounded; the reference runs a bounded tokio runtime). Streaming
  // connections (batch NDJSON to the trainer) occupy a worker for their
  // whole lifetime, so the default leaves generous headroom over the
  // handful of trainer + per-instance control connections.
  explicit Server(size_t workers = 64) : workers_(workers) {}

  void route(const std::string& method, const std::string& path, Handler h) {
    routes_[method + " " + path] = std::move(h);
  }

  // Invoked for every request BEFORE the handler runs (request counting,
  // trace echo). Set once before serve(); runs on worker threads.
  void set_observer(Handler fn) { observer_ = std::move(fn); }

  // bind+listen; returns the bound port (for port 0 = ephemeral) or -1.
  int listen(const std::string& host, int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    addr.sin_addr.s_addr = host.empty() || host == "0.0.0.0"
                               ? INADDR_ANY
                               : inet_addr(host.c_str());
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) return -1;
    if (::listen(listen_fd_, 128) < 0) return -1;
    socklen_t len = sizeof(addr);
    getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    return ntohs(addr.sin_port);
  }

  void serve() {
    running_ = true;
    pool_ = std::make_unique<WorkerPool>(workers_);
    while (running_) {
      sockaddr_in peer{};
      socklen_t plen = sizeof(peer);
      int fd = ::accept(listen_fd_, reinterpret_cast<sockaddr*>(&peer), &plen);
      if (fd < 0) {
        if (!running_) break;
        continue;
      }
      char ip[INET_ADDRSTRLEN] = {0};
      inet_ntop(AF_INET, &peer.sin_addr, ip, sizeof(ip));
      std::string peer_ip(ip);
      if (!pool_->submit([this, fd, peer_ip] { handle_conn(fd, peer_ip); }))
        ::close(fd);
    }
    pool_->stop();
  }

  void stop() {
    running_ = false;
    // unblock serve() even when it is parked in pool_->submit() on a full
    // queue (connection saturation) — stop() wakes the not_full_ waiters
    if (pool_) pool_->stop();
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
  }

 private:
  void handle_conn(int fd, const std::string& peer_ip = std::string()) {
    Request req;
    req.peer_ip = peer_ip;
    if (read_request(fd, req)) {
      ResponseWriter rw(fd);
      if (observer_) observer_(req, rw);
      auto it = routes_.find(req.method + " " + req.path);
      if (it == routes_.end()) {
        rw.status = 404;
        rw.body = "{\"error\":\"not found\"}";
      } else {
        try {
          it->second(req, rw);
        } catch (const std::exception& e) {
          if (!rw.streaming()) {
            rw.status = 500;
            rw.body = std::string("{\"error\":\"") + e.what() + "\"}";
          }
        }
      }
      rw.finish();
    }
    ::close(fd);
  }

  static bool read_request(int fd, Request& req) {
    std::string buf;
    char tmp[8192];
    size_t header_end = std::string::npos;
    while (header_end == std::string::npos) {
      ssize_t n = ::recv(fd, tmp, sizeof(tmp), 0);
      if (n <= 0) return false;
      buf.append(tmp, static_cast<size_t>(n));
      header_end = buf.find("\r\n\r\n");
      if (buf.size() > (16u << 20)) return false;
    }
    // request line
    size_t line_end = buf.find("\r\n");
    std::string line = buf.substr(0, line_end);
    size_t sp1 = line.find(' ');
    size_t sp2 = line.find(' ', sp1 + 1);
    if (sp1 == std::string::npos || sp2 == std::string::npos) return false;
    req.method = line.substr(0, sp1);
    std::string target = line.substr(sp1 + 1, sp2 - sp1 - 1);
    size_t q = target.find('?');
    req.path = q == std::string::npos ? target : target.substr(0, q);
    req.query = q == std::string::npos ? "" : target.substr(q + 1);
    // headers
    size_t pos = line_end + 2;
    while (pos < header_end) {
      size_t eol = buf.find("\r\n", pos);
      std::string h = buf.substr(pos, eol - pos);
      size_t colon = h.find(':');
      if (colon != std::string::npos) {
        std::string key = h.substr(0, colon);
        for (auto& c : key) c = static_cast<char>(tolower(c));
        size_t vstart = h.find_first_not_of(' ', colon + 1);
        req.headers[key] = vstart == std::string::npos ? "" : h.substr(vstart);
      }
      pos = eol + 2;
    }
    size_t content_len = 0;
    auto it = req.headers.find("content-length");
    if (it != req.headers.end()) {
      try {
        content_len = std::stoul(it->second);
      } catch (const std::exception&) {
        return false;  // malformed header: drop the connection, not the server
      }
      if (content_len > (64u << 20)) return false;
    }
    req.body = buf.substr(header_end + 4);
    while (req.body.size() < content_len) {
      ssize_t n = ::recv(fd, tmp, sizeof(tmp), 0);
      if (n <= 0) return false;
      req.body.append(tmp, static_cast<size_t>(n));
    }
    return true;
  }

  std::map<std::string, Handler> routes_;
  Handler observer_;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  size_t workers_;
  std::unique_ptr<WorkerPool> pool_;
};

// ---- client ---------------------------------------------------------------

struct ClientResponse {
  int status = 0;
  std::string body;
  bool ok() const { return status >= 200 && status < 300; }
};

// "host:port" → (host, port)
inline bool split_endpoint(const std::string& ep, std::string& host, int& port) {
  std::string s = ep;
  auto scheme = s.find("://");
  if (scheme != std::string::npos) s = s.substr(scheme + 3);
  auto slash = s.find('/');
  if (slash != std::string::npos) s = s.substr(0, slash);
  auto colon = s.rfind(':');
  if (colon == std::string::npos) return false;
  host = s.substr(0, colon);
  port = std::stoi(s.substr(colon + 1));
  return true;
}

class ClientConn {
 public:
  ~ClientConn() { close(); }

  bool connect(const std::string& host, int port, int timeout_ms) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0) return false;
    fd_ = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd_ < 0) { freeaddrinfo(res); return false; }
    set_timeout(timeout_ms);
    int rc = ::connect(fd_, res->ai_addr, res->ai_addrlen);
    freeaddrinfo(res);
    if (rc != 0) { close(); return false; }
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    return true;
  }

  void set_timeout(int timeout_ms) {
    if (fd_ < 0) return;
    timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  }

  bool send_request(const std::string& method, const std::string& host,
                    const std::string& path, const std::string& body,
                    const std::string& content_type = "application/json") {
    std::string req = method + " " + path + " HTTP/1.1\r\nHost: " + host +
                      "\r\nContent-Type: " + content_type +
                      "\r\nContent-Length: " + std::to_string(body.size()) +
                      "\r\nConnection: close\r\n\r\n" + body;
    return write_all(req.data(), req.size());
  }

  // Read status line + headers; leaves body streaming via read_line/read_rest.
  bool read_header(int& status) {
    while (true) {
      size_t he = buf_.find("\r\n\r\n");
      if (he != std::string::npos) {
        size_t le = buf_.find("\r\n");
        std::string line = buf_.substr(0, le);
        size_t sp = line.find(' ');
        status = 0;
        if (sp != std::string::npos) {
          try {
            status = std::stoi(line.substr(sp + 1, 3));
          } catch (const std::exception&) {
            return false;  // malformed status line
          }
        }
        std::string headers_lower = buf_.substr(0, he);
        for (auto& c : headers_lower) c = static_cast<char>(tolower(c));
        chunked_ = headers_lower.find("transfer-encoding: chunked") != std::string::npos;
        buf_.erase(0, he + 4);
        return true;
      }
      if (!fill()) return false;
    }
  }

  // Next logical line of the (possibly chunked) body; false on EOF/error.
  bool read_line(std::string& line) {
    while (true) {
      size_t nl = decoded_.find('\n');
      if (nl != std::string::npos) {
        line = decoded_.substr(0, nl);
        if (!line.empty() && line.back() == '\r') line.pop_back();
        decoded_.erase(0, nl + 1);
        return true;
      }
      if (!pump()) {
        if (!decoded_.empty()) {
          line = std::move(decoded_);
          decoded_.clear();
          return true;
        }
        return false;
      }
    }
  }

  std::string read_rest() {
    while (pump()) {}
    std::string out = std::move(decoded_);
    decoded_.clear();
    return out;
  }

  void close() {
    if (fd_ >= 0) { ::close(fd_); fd_ = -1; }
  }

 private:
  bool fill() {
    char tmp[16384];
    ssize_t n = ::recv(fd_, tmp, sizeof(tmp), 0);
    if (n <= 0) return false;
    buf_.append(tmp, static_cast<size_t>(n));
    return true;
  }

  // move decoded body bytes from buf_ into decoded_; false when body ends.
  bool pump() {
    if (!chunked_) {
      if (buf_.empty() && !fill()) return false;
      decoded_ += buf_;
      buf_.clear();
      return true;
    }
    while (true) {
      size_t le = buf_.find("\r\n");
      if (le == std::string::npos) {
        if (!fill()) return false;
        continue;
      }
      size_t chunk_len = 0;
      try {
        chunk_len = std::stoul(buf_.substr(0, le), nullptr, 16);
      } catch (const std::exception&) {
        return false;  // garbage chunk-size line from a half-dead peer
      }
      if (chunk_len == 0) return false;  // final chunk
      while (buf_.size() < le + 2 + chunk_len + 2) {
        if (!fill()) return false;
      }
      decoded_.append(buf_, le + 2, chunk_len);
      buf_.erase(0, le + 2 + chunk_len + 2);
      return true;
    }
  }

  bool write_all(const char* data, size_t len) {
    size_t off = 0;
    while (off < len) {
      ssize_t n = ::send(fd_, data + off, len - off, MSG_NOSIGNAL);
      if (n <= 0) return false;
      off += static_cast<size_t>(n);
    }
    return true;
  }

  int fd_ = -1;
  std::string buf_;
  std::string decoded_;
  bool chunked_ = false;
};

// One-shot convenience request.
inline ClientResponse request(const std::string& method, const std::string& endpoint,
                              const std::string& path, const std::string& body,
                              int timeout_ms = 5000) {
  ClientResponse resp;
  std::string host;
  int port;
  if (!split_endpoint(endpoint, host, port)) return resp;
  ClientConn conn;
  if (!conn.connect(host, port, timeout_ms)) return resp;
  if (!conn.send_request(method, host, path, body)) return resp;
  if (!conn.read_header(resp.status)) return resp;
  resp.body = conn.read_rest();
  return resp;
}

}  // namespace phttp
