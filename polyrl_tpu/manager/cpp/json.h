// Minimal JSON DOM: parse/serialize, no external deps.
// Part of the TPU-native rollout manager (C++ equivalent of the reference's
// Rust rollout-manager, SURVEY.md C16; serde role).
#pragma once

#include <cmath>
#include <cstdint>
#include <limits>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace pjson {

class Value;
using Array = std::vector<Value>;
using Object = std::map<std::string, Value>;

class Value {
 public:
  enum class Type { Null, Bool, Num, Str, Arr, Obj };

  Value() : type_(Type::Null) {}
  Value(std::nullptr_t) : type_(Type::Null) {}
  Value(bool b) : type_(Type::Bool), bool_(b) {}
  Value(int v) : type_(Type::Num), num_(v) {}
  Value(int64_t v) : type_(Type::Num), num_(static_cast<double>(v)) {}
  Value(size_t v) : type_(Type::Num), num_(static_cast<double>(v)) {}
  Value(double v) : type_(Type::Num), num_(v) {}
  Value(const char* s) : type_(Type::Str), str_(s) {}
  Value(std::string s) : type_(Type::Str), str_(std::move(s)) {}
  Value(Array a) : type_(Type::Arr), arr_(std::make_shared<Array>(std::move(a))) {}
  Value(Object o) : type_(Type::Obj), obj_(std::make_shared<Object>(std::move(o))) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_bool() const { return type_ == Type::Bool; }
  bool is_num() const { return type_ == Type::Num; }
  bool is_str() const { return type_ == Type::Str; }
  bool is_arr() const { return type_ == Type::Arr; }
  bool is_obj() const { return type_ == Type::Obj; }

  bool as_bool(bool dflt = false) const { return is_bool() ? bool_ : dflt; }
  double as_num(double dflt = 0) const { return is_num() ? num_ : dflt; }
  int64_t as_int(int64_t dflt = 0) const {
    // non-finite → dflt: casting NaN/Inf to int64 is UB, and the parser
    // can legitimately produce such values from engine streams
    return is_num() && std::isfinite(num_) ? static_cast<int64_t>(num_) : dflt;
  }
  const std::string& as_str() const {
    static const std::string empty;
    return is_str() ? str_ : empty;
  }
  const Array& as_arr() const {
    static const Array empty;
    return is_arr() ? *arr_ : empty;
  }
  Array& mut_arr() {
    if (!is_arr()) { type_ = Type::Arr; arr_ = std::make_shared<Array>(); }
    return *arr_;
  }
  const Object& as_obj() const {
    static const Object empty;
    return is_obj() ? *obj_ : empty;
  }
  Object& mut_obj() {
    if (!is_obj()) { type_ = Type::Obj; obj_ = std::make_shared<Object>(); }
    return *obj_;
  }

  // object field access (null if missing)
  const Value& operator[](const std::string& k) const {
    static const Value null_v;
    if (!is_obj()) return null_v;
    auto it = obj_->find(k);
    return it == obj_->end() ? null_v : it->second;
  }
  bool has(const std::string& k) const {
    return is_obj() && obj_->count(k) > 0;
  }
  void set(const std::string& k, Value v) { mut_obj()[k] = std::move(v); }

  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

  void write(std::ostream& os) const {
    switch (type_) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_ ? "true" : "false"); break;
      case Type::Num: {
        if (std::isnan(num_)) {
          // match Python's json: "nan"/"inf" from ostream would be
          // unparseable on the trainer side, killing the whole stream
          os << "NaN";
        } else if (std::isinf(num_)) {
          os << (num_ < 0 ? "-Infinity" : "Infinity");
        } else if (num_ == std::floor(num_) && std::fabs(num_) < 9.0e15) {
          os << static_cast<int64_t>(num_);
        } else {
          std::ostringstream tmp;
          tmp.precision(17);
          tmp << num_;
          os << tmp.str();
        }
        break;
      }
      case Type::Str: write_escaped(os, str_); break;
      case Type::Arr: {
        os << '[';
        bool first = true;
        for (const auto& v : *arr_) {
          if (!first) os << ',';
          first = false;
          v.write(os);
        }
        os << ']';
        break;
      }
      case Type::Obj: {
        os << '{';
        bool first = true;
        for (const auto& [k, v] : *obj_) {
          if (!first) os << ',';
          first = false;
          write_escaped(os, k);
          os << ':';
          v.write(os);
        }
        os << '}';
        break;
      }
    }
  }

 private:
  static void write_escaped(std::ostream& os, const std::string& s) {
    os << '"';
    for (char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  std::shared_ptr<Array> arr_;
  std::shared_ptr<Object> obj_;
};

// ---- parser ---------------------------------------------------------------

class Parser {
 public:
  explicit Parser(const std::string& s) : s_(s) {}

  Value parse() {
    skip_ws();
    Value v = parse_value();
    skip_ws();
    return v;
  }

  static Value parse(const std::string& s, bool* ok = nullptr) {
    try {
      Parser p(s);
      Value v = p.parse();
      if (ok) *ok = true;
      return v;
    } catch (const std::exception&) {
      if (ok) *ok = false;
      return Value();
    }
  }

 private:
  void skip_ws() {
    while (i_ < s_.size() && (s_[i_] == ' ' || s_[i_] == '\t' || s_[i_] == '\n' || s_[i_] == '\r'))
      ++i_;
  }
  char peek() {
    if (i_ >= s_.size()) throw std::runtime_error("json: eof");
    return s_[i_];
  }
  char next() {
    char c = peek();
    ++i_;
    return c;
  }
  void expect(const char* lit) {
    for (const char* p = lit; *p; ++p)
      if (i_ >= s_.size() || s_[i_++] != *p) throw std::runtime_error("json: bad literal");
  }

  Value parse_value() {
    skip_ws();
    char c = peek();
    switch (c) {
      case '{': return parse_object();
      case '[': return parse_array();
      case '"': return Value(parse_string());
      case 't': expect("true"); return Value(true);
      case 'f': expect("false"); return Value(false);
      case 'n': expect("null"); return Value();
      default: return parse_number();
    }
  }

  Value parse_object() {
    next();  // {
    Object o;
    skip_ws();
    if (peek() == '}') { next(); return Value(std::move(o)); }
    while (true) {
      skip_ws();
      std::string key = parse_string();
      skip_ws();
      if (next() != ':') throw std::runtime_error("json: expected :");
      o[std::move(key)] = parse_value();
      skip_ws();
      char c = next();
      if (c == '}') break;
      if (c != ',') throw std::runtime_error("json: expected , or }");
    }
    return Value(std::move(o));
  }

  Value parse_array() {
    next();  // [
    Array a;
    skip_ws();
    if (peek() == ']') { next(); return Value(std::move(a)); }
    while (true) {
      a.push_back(parse_value());
      skip_ws();
      char c = next();
      if (c == ']') break;
      if (c != ',') throw std::runtime_error("json: expected , or ]");
    }
    return Value(std::move(a));
  }

  std::string parse_string() {
    if (next() != '"') throw std::runtime_error("json: expected string");
    std::string out;
    while (true) {
      char c = next();
      if (c == '"') break;
      if (c == '\\') {
        char e = next();
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            unsigned code = 0;
            for (int k = 0; k < 4; ++k) {
              char h = next();
              code <<= 4;
              if (h >= '0' && h <= '9') code |= h - '0';
              else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
              else throw std::runtime_error("json: bad \\u");
            }
            // utf-8 encode (BMP only; surrogate pairs folded naively)
            if (code < 0x80) out += static_cast<char>(code);
            else if (code < 0x800) {
              out += static_cast<char>(0xC0 | (code >> 6));
              out += static_cast<char>(0x80 | (code & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (code >> 12));
              out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (code & 0x3F));
            }
            break;
          }
          default: throw std::runtime_error("json: bad escape");
        }
      } else {
        out += c;
      }
    }
    return out;
  }

  Value parse_number() {
    size_t start = i_;
    bool neg = false;
    if (peek() == '-') { neg = true; next(); }
    // Python's json.dumps emits NaN/Infinity/-Infinity for non-finite
    // floats (not valid JSON, but real engines under test have produced
    // them) — parse the EXACT literals instead of throwing, so one bad
    // float can't kill a whole stream. Anything else alphabetic is still a
    // decode error (a plaintext body must not silently become Infinity).
    if (peek() == 'N' || peek() == 'I') {
      size_t lit_start = i_;
      while (i_ < s_.size() && isalpha(s_[i_])) ++i_;
      std::string lit = s_.substr(lit_start, i_ - lit_start);
      if (lit == "NaN")
        return Value(std::numeric_limits<double>::quiet_NaN());  // -NaN == NaN
      if (lit == "Infinity")
        return Value(neg ? -std::numeric_limits<double>::infinity()
                         : std::numeric_limits<double>::infinity());
      throw std::runtime_error("json: bad literal " + lit);
    }
    while (i_ < s_.size() && (isdigit(s_[i_]) || s_[i_] == '.' || s_[i_] == 'e' ||
                              s_[i_] == 'E' || s_[i_] == '+' || s_[i_] == '-'))
      ++i_;
    return Value(std::stod(s_.substr(start, i_ - start)));
  }

  const std::string& s_;
  size_t i_ = 0;
};

}  // namespace pjson
