// Instance registry + scheduler + weight-sender assignment.
//
// C++ equivalent of the reference manager's state.rs (SURVEY.md C16a):
// remote/local instance registries with atomic telemetry, pending set,
// active pool, quota + zero-queue round-robin scheduling
// (state.rs:84-147), round-robin weight-sender assignment (:149-162),
// weight-version orchestration, graceful shutdown (:224-270).
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <vector>

#include "balance.h"

namespace manager {

struct Instance {
  std::string endpoint;          // host:port of the rollout engine HTTP server
  bool is_local = false;         // colocated with the trainer (time-sliced)
  int group_idx = 0;             // weight-sender group assignment
  std::string weight_sender;     // assigned sender endpoint ("" = none yet)

  // telemetry (stats poller writes, scheduler reads)
  std::atomic<int64_t> num_running_reqs{0};
  std::atomic<int64_t> num_queued_reqs{0};
  std::atomic<double> last_gen_throughput{0.0};
  std::atomic<int64_t> assigned_batches{0};
  std::atomic<bool> updating_weight{false};
  std::atomic<int64_t> weight_version{-1};
  std::atomic<bool> healthy{false};
  // elastic-pool membership state: consecutive heartbeat (stats-poll)
  // misses — a remote past the configured budget is evicted; draining is
  // the engine's own announcement (server_info) that it took a preemption
  // notice — it leaves the routing set immediately but stays registered
  // until it deregisters or its heartbeat lapses
  std::atomic<int64_t> heartbeat_misses{0};
  std::atomic<bool> draining{false};
  // engine flight-deck telemetry (stats poller forwards from server_info):
  // decode slot occupancy (EWMA), page-pool utilization, server-side
  // latency tails, prefix-cache hit rate, speculative acceptance, and the
  // token-accounting reconciliation ratio — the per-engine load signals a
  // placement layer needs beyond num_running_reqs. Engines that predate
  // the flight deck simply never write them (zeros / frac 1.0).
  std::atomic<double> occupancy{0.0};
  std::atomic<double> page_util{0.0};
  std::atomic<double> ttft_p95_s{0.0};
  std::atomic<double> tpot_p95_s{0.0};
  std::atomic<double> cache_hit_rate{0.0};
  std::atomic<double> spec_accept_rate{0.0};
  std::atomic<double> attributed_frac{1.0};
  // group-shared prefill telemetry: fraction of prompt tokens served from
  // shared/cached pages, and the request-level (length-unbiased) prefix
  // hit fraction
  std::atomic<double> prefill_reuse_frac{0.0};
  std::atomic<double> prefix_hit_frac{0.0};
  // KV memory plane telemetry (rollout/kvledger.py): fraction of resident
  // pages gone cold (idle past the tier threshold) and device HBM headroom
  // in GB. headroom < 0 sentinels "not reported" (CPU engines / ledger
  // off) so the fleet min never counts an unreporting engine as 0 GB.
  std::atomic<double> kv_cold_page_frac{0.0};
  std::atomic<double> hbm_headroom_gb{-1.0};
  // host-RAM KV spill tier (rollout/kvspill.py): fraction of the page pool
  // currently paged out to host RAM (can exceed 1.0 under oversubscription)
  // and the windowed restore rate in pages/dispatch (the thrash signal).
  std::atomic<double> kv_spilled_frac{0.0};
  std::atomic<double> kv_restore_rate{0.0};
  // engine-loop profiler (obs/engine_profile.py): windowed fraction of the
  // loop wall spent dispatching to / waiting on the device, and the
  // bookkeeping (deck+ledger+spill sweep) fraction. device_frac < 0
  // sentinels "not reported" (loop_profile off / pre-profiler engines) so
  // the fleet min never counts an unreporting engine as 0.
  std::atomic<double> device_frac{-1.0};
  std::atomic<double> accounting_frac{0.0};
};

using InstancePtr = std::shared_ptr<Instance>;

class AppState {
 public:
  explicit AppState(int max_assigned_batches = 4)
      : max_assigned_batches_(max_assigned_batches) {}

  // -- registration ----------------------------------------------------

  // Returns assigned (weight_sender, group_idx). Instance starts pending
  // until promote_healthy.
  std::pair<std::string, int> register_instance(const std::string& endpoint,
                                                bool is_local) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = instances_.find(endpoint);
    InstancePtr inst;
    if (it != instances_.end()) {
      inst = it->second;
    } else {
      inst = std::make_shared<Instance>();
      inst->endpoint = endpoint;
      instances_[endpoint] = inst;
    }
    inst->is_local = is_local;
    if (inst->weight_sender.empty() && !weight_senders_.empty()) {
      auto [sender, group] = next_sender_locked();
      inst->weight_sender = sender;
      inst->group_idx = group;
    }
    // a re-registration (rejoin after drain/eviction of the same endpoint)
    // starts with a clean bill: no inherited misses or draining flag
    inst->heartbeat_misses = 0;
    inst->draining = false;
    ++joins_;
    if (is_local) {
      // local engines are trusted healthy (they registered from in-process)
      inst->healthy = true;
      active_.insert(endpoint);
      cv_.notify_all();
    } else {
      pending_.insert(endpoint);
    }
    return {inst->weight_sender, inst->group_idx};
  }

  void promote_healthy(const std::string& endpoint) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = instances_.find(endpoint);
    if (it == instances_.end()) return;
    it->second->healthy = true;
    pending_.erase(endpoint);
    // joins the ACTIVE pool only after weight bootstrap (get_receive_instances
    // → update_weights), mirroring handlers.rs:40-86 — UNLESS the instance
    // already reports the pool's current weight version (a reconcile replay
    // of a healthy fleet after a manager respawn: those engines would never
    // be offered to a sender and would strand outside the routing set
    // forever). With no senders registered (no weight fabric), it goes
    // straight to active.
    if (weight_senders_.empty() ||
        it->second->weight_version.load() >= weight_version_) {
      active_.insert(endpoint);
      cv_.notify_all();
    }
  }

  // Reconcile replay: restore a replayed engine's last-known weight version
  // (monotonic per instance — a stale replay can never rewind a live
  // engine), then re-admit it to the routing set if it is healthy and at
  // the current pool version (the respawned manager must not orphan a
  // caught-up fleet behind a redundant weight bootstrap).
  void set_instance_version(const std::string& endpoint, int64_t version) {
    // versions from real trainer pushes are >= 1 (update_weight_version
    // pre-increments from 0); a reported 0 is an engine's random-init
    // weights and must NOT satisfy the bootstrap gate
    if (version <= 0) return;
    std::lock_guard<std::mutex> g(mu_);
    auto it = instances_.find(endpoint);
    if (it == instances_.end()) return;
    auto& inst = it->second;
    if (version > inst->weight_version.load()) inst->weight_version = version;
    // re-admission is for caught-up REMOTES only: a time-sliced-out local
    // re-enters exclusively via resume_local_instances, and an instance
    // mid-weight-update re-enters via complete_weight_update
    if (!inst->is_local && inst->healthy.load() && !inst->draining.load() &&
        !inst->updating_weight.load() &&
        inst->weight_version.load() >= weight_version_) {
      active_.insert(endpoint);
      cv_.notify_all();
    }
  }

  // The engine announced it is draining (preemption notice): out of the
  // routing set immediately, but it stays registered — in-flight aborts are
  // still being flushed as salvageable partials through its wire.
  void mark_draining(const std::string& endpoint) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = instances_.find(endpoint);
    if (it == instances_.end()) return;
    if (!it->second->draining.exchange(true)) ++drain_departures_;
    active_.erase(endpoint);
  }

  // Heartbeat-timeout eviction (scale-down WITHOUT notice): forget the
  // instance and count the eviction. In-flight rids on it fail their
  // stream and continue on survivors through the normal salvage path.
  void evict(const std::string& endpoint) {
    std::lock_guard<std::mutex> g(mu_);
    if (!instances_.count(endpoint)) return;
    active_.erase(endpoint);
    pending_.erase(endpoint);
    instances_.erase(endpoint);
    ++evictions_;
  }

  // Graceful leave (POST /deregister_rollout_instance): the engine (or the
  // pool manager running a preemption drill) announced departure. A drain
  // the heartbeat already booked (mark_draining) is not counted twice.
  void leave(const std::string& endpoint, bool drained) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = instances_.find(endpoint);
    if (it == instances_.end()) return;
    bool already_draining = it->second->draining.load();
    active_.erase(endpoint);
    pending_.erase(endpoint);
    instances_.erase(it);
    if (drained) {
      if (!already_draining) ++drain_departures_;
    } else {
      ++evictions_;
    }
  }

  struct PoolCounts {
    int64_t joins = 0, evictions = 0, drain_departures = 0;
    int64_t active = 0, pending = 0, registered = 0;
  };

  PoolCounts pool_counts() {
    std::lock_guard<std::mutex> g(mu_);
    PoolCounts out;
    out.joins = joins_;
    out.evictions = evictions_;
    out.drain_departures = drain_departures_;
    out.active = static_cast<int64_t>(active_.size());
    out.pending = static_cast<int64_t>(pending_.size());
    out.registered = static_cast<int64_t>(instances_.size());
    return out;
  }

  bool is_active(const std::string& endpoint) {
    std::lock_guard<std::mutex> g(mu_);
    return active_.count(endpoint) > 0;
  }

  bool has_instance(const std::string& endpoint) {
    std::lock_guard<std::mutex> g(mu_);
    return instances_.count(endpoint) > 0;
  }

  void deregister(const std::string& endpoint) {
    std::lock_guard<std::mutex> g(mu_);
    active_.erase(endpoint);
    pending_.erase(endpoint);
    instances_.erase(endpoint);
  }

  InstancePtr get(const std::string& endpoint) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = instances_.find(endpoint);
    return it == instances_.end() ? nullptr : it->second;
  }

  std::vector<InstancePtr> all_instances() {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<InstancePtr> out;
    for (auto& [_, inst] : instances_) out.push_back(inst);
    return out;
  }

  std::vector<InstancePtr> active_instances() {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<InstancePtr> out;
    for (auto& ep : active_) {
      auto it = instances_.find(ep);
      if (it != instances_.end()) out.push_back(it->second);
    }
    return out;
  }

  size_t active_count() {
    std::lock_guard<std::mutex> g(mu_);
    return active_.size();
  }

  // True while the pool can plausibly recover WITHOUT trainer action: an
  // instance is pending its health check, active-but-busy (quota/queue —
  // frees up on the next stats tick), or a drained remote mid-weight-update
  // (the sender poll loop re-admits it). Time-sliced-out LOCALS do NOT
  // count: their only re-admission path is resume_local_instances() at the
  // trainer's next stream, which cannot happen while this batch blocks —
  // waiting on them would deadlock a local-only pool at the window expiry.
  // Used by the scheduler to distinguish "busy, requeue" from "dead, fail"
  // (the reference blocks indefinitely, state.rs:84-147, but its pool is
  // remote-only).
  bool has_prospective_instances() {
    std::lock_guard<std::mutex> g(mu_);
    if (!pending_.empty()) return true;
    for (auto& [ep, inst] : instances_) {
      if (!inst->healthy.load()) continue;
      if (inst->draining.load()) continue;  // announced departure: leaving
      if (active_.count(ep)) return true;
      if (!inst->is_local) return true;
    }
    return false;
  }

  // -- scheduling (reference next_instance_with_type, state.rs:84-147) --

  // Block until an instance is available: quota not exhausted AND zero
  // queued requests; among eligible, pick the LEAST-LOADED (running +
  // queued from the last stats tick, plus batches assigned since — the
  // live signal between ticks), tie-broken round-robin so an idle pool
  // still rotates. want_local filters by locality (-1 = any). Returns
  // nullptr on shutdown/timeout.
  //
  // group_id (group-shared prefill): the first member of a group pins the
  // group to the picked endpoint; later members route to the pin even when
  // it is quota-busy (they WAIT for it rather than splitting the group
  // across engines — split siblings each pay a fresh prompt prefill,
  // structurally defeating the engine's shared-prefill fork). A pin whose
  // endpoint left the routing set (evicted/drained) is dropped and the
  // member re-pins to a survivor — the salvage continuation path then
  // carries the whole group there together.
  InstancePtr next_instance(int want_local = -1, int timeout_ms = 120000,
                            const std::string& group_id = std::string()) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    while (!shutdown_) {
      if (!group_id.empty()) {
        auto pin = group_pins_.find(group_id);
        if (pin != group_pins_.end()) {
          auto it = instances_.find(pin->second);
          bool routed = it != instances_.end() && active_.count(pin->second) &&
                        !it->second->draining.load();
          if (!routed) {
            group_pins_.erase(pin);  // endpoint gone: re-pin below
          } else {
            auto& inst = it->second;
            bool ok = (want_local < 0 ||
                       inst->is_local == (want_local == 1)) &&
                      !inst->updating_weight.load() &&
                      inst->assigned_batches.load() < max_assigned_batches_ &&
                      inst->num_queued_reqs.load() == 0;
            if (ok) {
              inst->assigned_batches.fetch_add(1);
              return inst;
            }
            // pinned but momentarily ineligible (quota/queue): wait for it
            // instead of splitting the group across engines
            if (cv_.wait_until(lk, deadline) == std::cv_status::timeout)
              return nullptr;
            continue;
          }
        }
      }
      std::vector<InstancePtr> eligible;
      for (auto& ep : active_) {
        auto it = instances_.find(ep);
        if (it == instances_.end()) continue;
        auto& inst = it->second;
        if (want_local >= 0 && inst->is_local != (want_local == 1)) continue;
        if (inst->updating_weight.load()) continue;
        if (inst->draining.load()) continue;
        if (inst->assigned_batches.load() >= max_assigned_batches_) continue;
        if (inst->num_queued_reqs.load() > 0) continue;
        eligible.push_back(inst);
      }
      if (!eligible.empty()) {
        auto load = [](const InstancePtr& i) {
          return i->num_running_reqs.load() + i->num_queued_reqs.load() +
                 i->assigned_batches.load();
        };
        size_t start = rr_counter_++ % eligible.size();
        InstancePtr pick = eligible[start];
        int64_t best = load(pick);
        for (size_t k = 1; k < eligible.size(); ++k) {
          auto& cand = eligible[(start + k) % eligible.size()];
          int64_t l = load(cand);
          if (l < best) { best = l; pick = cand; }
        }
        pick->assigned_batches.fetch_add(1);
        if (!group_id.empty()) pin_group_locked(group_id, pick->endpoint);
        return pick;
      }
      if (cv_.wait_until(lk, deadline) == std::cv_status::timeout) return nullptr;
    }
    return nullptr;
  }

  // stats tick: refresh quota + wake blocked schedulers (state.rs quota
  // reset each stats check).
  void reset_quotas() {
    std::lock_guard<std::mutex> g(mu_);
    for (auto& [_, inst] : instances_) inst->assigned_batches = 0;
    cv_.notify_all();
  }

  void notify_available() { cv_.notify_all(); }

  // -- weight-version orchestration (handlers.rs:566-649) ---------------

  // New trainer weights exist: drain the active pool (remote instances must
  // re-bootstrap through the sender), keep/re-add local instances (they get
  // weights in-process). With NO transfer fabric registered there is no
  // sender poll loop to re-admit a drained remote (reference re-admission:
  // sender_agent.py:324-340 → handlers.rs:681-795), so draining would
  // strand it forever — keep the pool as-is and only record the bump;
  // remotes serve stale weights until a fabric is attached.
  int64_t update_weight_version() {
    std::lock_guard<std::mutex> g(mu_);
    ++weight_version_;
    if (weight_senders_.empty()) {
      cv_.notify_all();
      return weight_version_;
    }
    std::set<std::string> next_active;
    for (auto& ep : active_) {
      auto it = instances_.find(ep);
      if (it != instances_.end() && it->second->is_local) next_active.insert(ep);
    }
    active_ = std::move(next_active);
    return weight_version_;
  }

  int64_t weight_version() {
    std::lock_guard<std::mutex> g(mu_);
    return weight_version_;
  }

  // Supervisor replay after a respawn (/reconcile): restore the version a
  // crashed predecessor had reached WITHOUT the drain semantics of
  // update_weight_version — the fresh registry has nothing to drain, and a
  // replayed bump must never re-trigger a pool reset. Monotonic: a stale
  // replay can only raise the version, never rewind it.
  int64_t raise_weight_version_floor(int64_t version) {
    std::lock_guard<std::mutex> g(mu_);
    if (version > weight_version_) weight_version_ = version;
    return weight_version_;
  }

  // Sender polls: return healthy instances whose weights are stale,
  // CAS-marking them updating (handlers.rs:602-649).
  std::vector<InstancePtr> get_receive_instances(const std::string& sender) {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<InstancePtr> out;
    for (auto& [_, inst] : instances_) {
      if (!inst->healthy.load()) continue;
      if (inst->is_local) continue;  // local engines get weights in-process
      if (!sender.empty() && inst->weight_sender != sender) continue;
      if (inst->weight_version.load() >= weight_version_) continue;
      bool expected = false;
      if (inst->updating_weight.compare_exchange_strong(expected, true)) {
        out.push_back(inst);
      }
    }
    return out;
  }

  // Transfer finished: record version, re-insert into the active pool,
  // wake blocked schedulers (handlers.rs:727-786). Invariant: only an
  // instance at the CURRENT version may re-enter the active pool — a push
  // that raced with a newer update_weight_version stays drained and is
  // re-pushed on the sender's next poll.
  void complete_weight_update(const std::string& endpoint, int64_t version) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = instances_.find(endpoint);
    if (it == instances_.end()) return;
    it->second->weight_version = version;
    it->second->updating_weight = false;
    if (version >= weight_version_) {
      active_.insert(endpoint);
      cv_.notify_all();
    }
  }

  void abort_weight_update(const std::string& endpoint) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = instances_.find(endpoint);
    if (it != instances_.end()) it->second->updating_weight = false;
  }

  // -- weight senders (launcher PUT /update_weight_senders) -------------

  void set_weight_senders(std::vector<std::string> senders, int groups_per_sender) {
    std::lock_guard<std::mutex> g(mu_);
    weight_senders_ = std::move(senders);
    groups_per_sender_ = std::max(groups_per_sender, 1);
  }

  std::vector<std::string> weight_senders() {
    std::lock_guard<std::mutex> g(mu_);
    return weight_senders_;
  }

  // -- local instance time-slicing (handlers.rs:500-513) ----------------

  // Pull local instances out of the pool (trainer wants the chips back).
  std::vector<InstancePtr> remove_local_from_active() {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<InstancePtr> out;
    for (auto it = active_.begin(); it != active_.end();) {
      auto inst_it = instances_.find(*it);
      if (inst_it != instances_.end() && inst_it->second->is_local) {
        out.push_back(inst_it->second);
        it = active_.erase(it);
      } else {
        ++it;
      }
    }
    return out;
  }

  void add_local_to_active() {
    std::lock_guard<std::mutex> g(mu_);
    for (auto& [ep, inst] : instances_) {
      if (inst->is_local && inst->healthy.load()) active_.insert(ep);
    }
    cv_.notify_all();
  }

  void shutdown() {
    std::lock_guard<std::mutex> g(mu_);
    shutdown_ = true;
    cv_.notify_all();
  }
  bool is_shutdown() {
    std::lock_guard<std::mutex> g(mu_);
    return shutdown_;
  }

  LoadBalanceState balance;

 private:
  std::pair<std::string, int> next_sender_locked() {
    // round-robin over senders × groups (state.rs:149-162)
    size_t total = weight_senders_.size() * static_cast<size_t>(groups_per_sender_);
    size_t idx = sender_rr_++ % std::max<size_t>(total, 1);
    size_t sender_idx = idx / groups_per_sender_;
    int group = static_cast<int>(idx % groups_per_sender_);
    return {weight_senders_[sender_idx], group};
  }

  // group-shared prefill routing pins (group_id -> endpoint), bounded FIFO:
  // groups are batch-lived, so the oldest pins are always dead weight —
  // evicting them cannot split a live group (its members arrive within one
  // batch_generate call, far fewer than kMaxGroupPins groups apart)
  static constexpr size_t kMaxGroupPins = 4096;
  void pin_group_locked(const std::string& group_id,
                        const std::string& endpoint) {
    if (group_pins_.emplace(group_id, endpoint).second) {
      group_pin_order_.push_back(group_id);
      while (group_pin_order_.size() > kMaxGroupPins) {
        group_pins_.erase(group_pin_order_.front());
        group_pin_order_.pop_front();
      }
    } else {
      group_pins_[group_id] = endpoint;
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, InstancePtr> instances_;
  std::set<std::string> active_;
  std::set<std::string> pending_;
  std::vector<std::string> weight_senders_;
  std::map<std::string, std::string> group_pins_;
  std::deque<std::string> group_pin_order_;
  int groups_per_sender_ = 1;
  size_t sender_rr_ = 0;
  size_t rr_counter_ = 0;
  int64_t weight_version_ = 0;
  int max_assigned_batches_;
  bool shutdown_ = false;
  // pool lifecycle counters (cumulative; /metrics + /get_instances_status)
  int64_t joins_ = 0;
  int64_t evictions_ = 0;
  int64_t drain_departures_ = 0;
};

}  // namespace manager
