// Token-level continuation math + small helpers.
//
// C++ equivalent of the reference's utils.rs (SURVEY.md C16e): merging
// partial responses (output_token_logprobs arrays + completion counts,
// utils.rs:19-86), extending input_ids with already-generated tokens
// (:140-182), and shrinking max_new_tokens by used tokens (:256-291) so a
// request evicted from a dying instance resumes on another one from the
// last generated token. Pure functions on JSON values — table-testable.
#pragma once

#include <string>
#include <vector>

#include "json.h"

namespace manager {

// Accumulated state of one in-flight request across attempts.
struct PartialResponse {
  std::vector<int64_t> token_ids;
  std::vector<double> logprobs;
  // per-token engine weight version (token-level continuous generation:
  // a resume that crosses a weight push stitches tokens sampled under
  // DIFFERENT policies — the trainer's truncated-importance correction
  // needs to know which). -1 = engine did not report one.
  std::vector<int64_t> weight_versions;
  std::string finish_reason;  // "" until finished
  bool finished = false;
};

// Fold one streamed chunk ({"token_ids":[...], "logprobs":[...],
// "finished":bool, "finish_reason":str, "weight_version":int?}) into the
// accumulator.
inline void merge_chunk(PartialResponse& acc, const pjson::Value& chunk) {
  int64_t wv = chunk["weight_version"].as_int(-1);
  for (const auto& t : chunk["token_ids"].as_arr()) {
    acc.token_ids.push_back(t.as_int());
    acc.weight_versions.push_back(wv);
  }
  for (const auto& l : chunk["logprobs"].as_arr())
    acc.logprobs.push_back(l.as_num());
  if (chunk["finished"].as_bool()) {
    acc.finished = true;
    acc.finish_reason = chunk["finish_reason"].as_str();
    if (acc.finish_reason.empty()) acc.finish_reason = "stop";
  }
}

// Build the continuation request: original prompt + generated-so-far tokens
// become the new prompt; the token budget shrinks by what was used.
// (reference extend_input_ids_with_response_tokens +
// adjust_sampling_params_for_used_tokens)
inline pjson::Value build_continuation_request(const pjson::Value& orig_request,
                                               const PartialResponse& partial) {
  pjson::Array new_ids;
  for (const auto& t : orig_request["input_ids"].as_arr()) new_ids.push_back(t);
  for (int64_t t : partial.token_ids) new_ids.push_back(pjson::Value(t));

  pjson::Object sp = orig_request["sampling_params"].as_obj();
  int64_t max_new = orig_request["sampling_params"]["max_new_tokens"].as_int(128);
  int64_t used = static_cast<int64_t>(partial.token_ids.size());
  sp["max_new_tokens"] = pjson::Value(std::max<int64_t>(max_new - used, 1));

  pjson::Object out = orig_request.as_obj();
  out["input_ids"] = pjson::Value(std::move(new_ids));
  out["sampling_params"] = pjson::Value(std::move(sp));
  return pjson::Value(std::move(out));
}

// Final response for the trainer: all attempts' tokens/logprobs merged.
inline pjson::Value build_final_response(const std::string& rid,
                                         const PartialResponse& acc) {
  pjson::Array ids, lps, wvs;
  for (int64_t t : acc.token_ids) ids.push_back(pjson::Value(t));
  for (double l : acc.logprobs) lps.push_back(pjson::Value(l));
  for (int64_t v : acc.weight_versions) wvs.push_back(pjson::Value(v));
  pjson::Object o;
  o["rid"] = pjson::Value(rid);
  o["success"] = pjson::Value(true);
  o["output_token_ids"] = pjson::Value(std::move(ids));
  o["output_token_logprobs"] = pjson::Value(std::move(lps));
  o["output_token_weight_versions"] = pjson::Value(std::move(wvs));
  o["finish_reason"] =
      pjson::Value(acc.finish_reason.empty() ? "abort" : acc.finish_reason);
  o["completion_tokens"] = pjson::Value(static_cast<int64_t>(acc.token_ids.size()));
  return pjson::Value(std::move(o));
}

inline pjson::Value error_response(const std::string& rid, const std::string& err) {
  pjson::Object o;
  o["rid"] = pjson::Value(rid);
  o["success"] = pjson::Value(false);
  o["error"] = pjson::Value(err);
  return pjson::Value(std::move(o));
}

}  // namespace manager
