// Fixed-size worker pool with a bounded task queue.
//
// Replaces the manager's thread-per-connection / thread-per-request spawning
// (round-1 review finding): the reference runs on a bounded tokio runtime,
// so a trainer submitting a 10k-request batch must not create 10k OS threads
// here. Submission BLOCKS when the queue is full (backpressure, matching
// tokio's bounded behavior) rather than dropping work.
#pragma once

#include <condition_variable>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace phttp {

class WorkerPool {
 public:
  explicit WorkerPool(size_t workers, size_t max_queue = 4096)
      : max_queue_(max_queue) {
    threads_.reserve(workers);
    for (size_t i = 0; i < workers; ++i) {
      threads_.emplace_back([this] { run(); });
    }
  }

  ~WorkerPool() { stop(); }

  // Blocks while the queue is full (backpressure). Returns false after stop().
  bool submit(std::function<void()> task) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      not_full_.wait(lk, [this] { return stopped_ || queue_.size() < max_queue_; });
      if (stopped_) return false;
      queue_.push_back(std::move(task));
    }
    not_empty_.notify_one();
    return true;
  }

  void stop() {
    {
      std::lock_guard<std::mutex> g(mu_);
      if (stopped_) return;
      stopped_ = true;
    }
    not_empty_.notify_all();
    not_full_.notify_all();
    for (auto& t : threads_) {
      if (t.joinable()) t.join();
    }
  }

  size_t size() const { return threads_.size(); }

 private:
  void run() {
    while (true) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        not_empty_.wait(lk, [this] { return stopped_ || !queue_.empty(); });
        if (queue_.empty()) return;  // stopped and drained
        task = std::move(queue_.front());
        queue_.pop_front();
      }
      not_full_.notify_one();
      task();
    }
  }

  std::mutex mu_;
  std::condition_variable not_empty_;
  std::condition_variable not_full_;
  std::deque<std::function<void()>> queue_;
  std::vector<std::thread> threads_;
  size_t max_queue_;
  bool stopped_ = false;
};

}  // namespace phttp
