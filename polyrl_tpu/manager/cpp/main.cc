// polyrl-manager — rollout control plane + fault-tolerant request router.
//
// C++ (TPU-native build) equivalent of the reference's Rust rollout-manager
// (SURVEY.md C16, rollout-manager/src/): instance registry + health checks
// + stats polling, quota/zero-queue round-robin scheduling, streaming
// generation routing with instance eviction and token-level continuation,
// local-engine time-slicing, adaptive local/remote balancing, and
// weight-version orchestration. Routes mirror main.rs:56-70.
//
// Build: make -C polyrl_tpu/manager/cpp   (→ polyrl-manager)

#include <signal.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>

#include "config.h"
#include "http.h"
#include "json.h"
#include "state.h"
#include "utils.h"

namespace manager {

using pjson::Array;
using pjson::Object;
using pjson::Value;

static void log_line(const std::string& msg) {
  // called from every worker/health/stats thread: localtime() hands back a
  // shared static buffer (TSAN-confirmed race) — use the reentrant form
  auto now = std::chrono::system_clock::to_time_t(std::chrono::system_clock::now());
  struct tm tm_buf;
  localtime_r(&now, &tm_buf);
  char buf[32];
  strftime(buf, sizeof(buf), "%H:%M:%S", &tm_buf);
  fprintf(stderr, "[manager %s] %s\n", buf, msg.c_str());
}

// Trace-context propagation (obs/trace.py): the trainer's client sends
// X-Trace-Id/X-Span-Id; the value is sanitized hard (it rides into log
// lines, response headers, and forwarded JSON) — anything outside
// [A-Za-z0-9._-] is dropped, length capped.
static std::string sanitize_trace(const std::string& raw) {
  std::string out;
  out.reserve(raw.size());
  for (char c : raw) {
    if (isalnum(static_cast<unsigned char>(c)) || c == '.' || c == '_' ||
        c == '-')
      out += c;
    if (out.size() >= 64) break;
  }
  return out;
}

static std::string header_of(const phttp::Request& req, const std::string& key) {
  auto it = req.headers.find(key);  // parsed keys are lowercased
  return it == req.headers.end() ? std::string() : sanitize_trace(it->second);
}

class Manager {
 public:
  explicit Manager(Config cfg)
      : cfg_(std::move(cfg)), state_(cfg_.max_assigned_batches_per_stats_check),
        gen_pool_(static_cast<size_t>(std::max(cfg_.generate_workers, 1))) {
    state_.balance.set_initial_gen_s(cfg_.initial_local_gen_s);
  }

  AppState& state() { return state_; }
  const Config& config() const { return cfg_; }

  // ---- generation with eviction + token-level continuation -------------
  // (reference process_single_generate_request, handlers.rs:330-418)

  // Per-chunk progress hook (token-level continuous generation): invoked
  // with each merged engine chunk so the batch stream can forward decoded
  // tokens to the trainer AS THEY ARRIVE. Without it, tokens accumulated
  // here die with this process on a SIGKILL and the trainer restarts the
  // whole request from token 0.
  using ProgressFn = std::function<void(const Value& chunk)>;

  Value process_generate(const Value& request, int want_local = -1,
                         const std::string& trace_id = std::string(),
                         const std::string& parent_span = std::string(),
                         const ProgressFn& progress = ProgressFn()) {
    std::string rid = request["rid"].as_str();
    // group-shared prefill: members of one GRPO group must land on ONE
    // engine (group-affinity pin inside next_instance) or each split
    // sibling pays a fresh prompt prefill
    std::string group_id = request["group_id"].as_str();
    PartialResponse acc;
    // inject the trainer's trace context into the request we forward (and
    // into every continuation built from it) so the engine's spans join
    // the same trace the trainer opened
    Value base = request;
    if (!trace_id.empty()) {
      pjson::Object o = base.as_obj();
      o["trace_id"] = Value(trace_id);
      o["parent_span"] = Value(parent_span);
      base = Value(std::move(o));
    }
    Value current = base;
    for (int attempt = 0; attempt < cfg_.max_generate_attempts; ++attempt) {
      InstancePtr inst = state_.next_instance(want_local,
                                              cfg_.schedule_wait_timeout_ms,
                                              group_id);
      if (!inst) {
        // Busy pool ≠ dead pool: while any healthy/pending instance exists
        // the request requeues without burning a retry attempt (matching the
        // reference's indefinitely-blocking scheduler, state.rs:84-147) —
        // a transiently busy pool must never destroy training data. Only an
        // actually empty pool (every instance evicted/unhealthy) fails.
        if (!state_.is_shutdown() && state_.has_prospective_instances()) {
          log_line("scheduler starved (pool busy), requeueing rid " + rid);
          --attempt;
          continue;
        }
        return error_response(rid, "no instance available");
      }
      // per-attempt rid suffix: engine-side request keys must be unique even
      // when a retry races the dying previous attempt's cleanup (fresh
      // Object: pjson copies alias the shared map)
      pjson::Object req_obj = current.as_obj();
      req_obj["rid"] = Value(rid + "#a" + std::to_string(attempt));
      Value attempt_req(std::move(req_obj));
      bool request_error = false;
      bool finished = stream_from_instance(inst, attempt_req, acc,
                                           request_error, progress);
      // assigned_batches is a RATE quota: incremented on assignment, zeroed
      // by the stats tick — never decremented (reference state.rs:84-147).
      state_.notify_available();
      if (finished) return build_final_response(rid, acc);
      // Transport/decode failure: evict remote instances (shutdown +
      // deregister), keep locals (they fail by abort during time-slicing,
      // not by dying). A REQUEST-level engine error (finish_reason=error)
      // retries without eviction — one bad request must not shut down up
      // to max_generate_attempts healthy engines.
      if (!inst->is_local && !request_error) {
        log_line("evicting instance " + inst->endpoint + " after stream failure");
        state_.evict(inst->endpoint);
        std::string ep = inst->endpoint;
        std::thread([ep] { phttp::request("POST", ep, "/shutdown", "{}", 2000); }).detach();
      }
      if (!acc.token_ids.empty()) {
        current = build_continuation_request(base, acc);
      }
    }
    if (!acc.token_ids.empty()) {
      // give the trainer what we have (partial, marked abort)
      acc.finished = false;
      acc.finish_reason = "abort";
      return build_final_response(rid, acc);
    }
    return error_response(rid, "max attempts exhausted");
  }

  // Stream one attempt; true iff the instance reported finished.
  // ``request_error`` is set when the ENGINE reported a request-level error
  // (finish_reason=error) — the instance itself is healthy.
  bool stream_from_instance(const InstancePtr& inst, const Value& request,
                            PartialResponse& acc, bool& request_error,
                            const ProgressFn& progress = ProgressFn()) {
    std::string host;
    int port;
    if (!phttp::split_endpoint(inst->endpoint, host, port)) return false;
    phttp::ClientConn conn;
    if (!conn.connect(host, port, cfg_.generate_timeout_ms)) return false;
    // fresh top-level object: pjson::Value copies alias the shared Object,
    // so set() on a plain copy would mutate the caller's request.
    pjson::Object req_obj = request.as_obj();
    req_obj["stream"] = Value(true);
    Value req(std::move(req_obj));
    if (!conn.send_request("POST", host, "/generate", req.dump())) return false;
    int status = 0;
    if (!conn.read_header(status) || status != 200) return false;
    std::string line;
    while (conn.read_line(line)) {
      if (line.empty()) continue;
      // accept SGLang-style "data: {...}" or bare NDJSON
      if (line.rfind("data:", 0) == 0) line = line.substr(5);
      bool ok = false;
      Value chunk = pjson::Parser::parse(line, &ok);
      if (!ok) return false;  // decode error → eviction path
      if (chunk["finish_reason"].as_str() == "abort") {
        // abort = preemption; the terminal line may CARRY salvaged tokens
        // (a salvage-enabled engine drains its pipeline into the partial)
        merge_chunk(acc, chunk);
        if (progress && !chunk["token_ids"].as_arr().empty()) progress(chunk);
        acc.finished = false;  // abort = time-slice preemption → continue elsewhere
        acc.finish_reason.clear();
        return false;
      }
      if (chunk["finish_reason"].as_str() == "error") {
        // engine-reported failure (e.g. duplicate rid, prefill error): the
        // attempt failed — retry on another instance. Treating it as a
        // finished stream would return success with an empty completion
        // and silently poison the training batch.
        request_error = true;
        return false;
      }
      merge_chunk(acc, chunk);
      if (progress && !chunk["token_ids"].as_arr().empty()) progress(chunk);
      if (acc.finished) return true;
    }
    return acc.finished;
  }

  // ---- batch generate: NDJSON stream with time-sliced local engines ----
  // (reference timed_batch_generate_requests, handlers.rs:442-564)

  void batch_generate(const Value& body, phttp::ResponseWriter& rw,
                      const std::string& trace_id = std::string(),
                      const std::string& parent_span = std::string()) {
    const Array& requests = body["requests"].as_arr();
    double max_local_gen_s = body["max_local_gen_s"].is_num()
                                 ? body["max_local_gen_s"].as_num()
                                 : state_.balance.max_local_gen_s();
    auto t_start = std::chrono::steady_clock::now();

    rw.content_type = "application/x-ndjson";
    if (!rw.start_stream()) return;
    // first line = notifier: the batch was accepted (the trainer's local
    // engines may now context-switch, stream_batch_iter.py:41-43)
    rw.write_chunk("{\"type\":\"notifier\"}\n");

    std::mutex mu;
    std::condition_variable cv;
    std::deque<std::string> ready;
    size_t remaining = requests.size();
    std::atomic<int64_t> total_resp_tokens{0};

    // time-slice watchdog: after the local window, pull local engines from
    // the pool and abort their in-flight requests (handlers.rs:500-513).
    // Started BEFORE the submit loop — submit can block on gen-pool
    // backpressure, and the window is promised from batch start.
    std::atomic<bool> batch_done{false};
    std::thread watchdog([this, max_local_gen_s, &batch_done] {
      double waited = 0;
      while (!batch_done.load() && waited < max_local_gen_s) {
        std::this_thread::sleep_for(std::chrono::milliseconds(200));
        waited += 0.2;
      }
      if (batch_done.load()) return;
      auto locals = state_.remove_local_from_active();
      for (auto& inst : locals) {
        log_line("time-slice: aborting local instance " + inst->endpoint +
                 " after " + std::to_string(max_local_gen_s) + "s");
        phttp::request("POST", inst->endpoint, "/abort_request", "{\"abort_all\":true}", 2000);
      }
    });

    // bounded request concurrency via the shared generate pool (round-1
    // finding: thread-per-request was unbounded). submit() applies
    // backpressure when the pool queue fills; results drain concurrently
    // below, so a huge batch just streams through generate_workers at a
    // time. Everything the task touches stays alive until remaining == 0,
    // which the drain loop waits for before returning.
    for (const auto& r : requests) {
      bool ok = gen_pool_.submit(
          [this, r, trace_id, parent_span, &mu, &cv, &ready, &remaining,
           &total_resp_tokens] {
            // token-level progress forwarding: every merged engine chunk
            // becomes a {"type":"progress"} NDJSON line on the trainer
            // stream, so the trainer's salvage ledger survives a manager
            // death — it re-issues prompt+salvaged instead of re-decoding
            const std::string rid = r["rid"].as_str();
            ProgressFn progress = [rid, &mu, &cv, &ready](const Value& chunk) {
              Object o;
              o["type"] = Value("progress");
              o["rid"] = Value(rid);
              o["token_ids"] = chunk["token_ids"];
              o["logprobs"] = chunk["logprobs"];
              o["weight_version"] = Value(chunk["weight_version"].as_int(-1));
              std::lock_guard<std::mutex> g(mu);
              ready.push_back(Value(std::move(o)).dump() + "\n");
              cv.notify_all();
            };
            Value resp = process_generate(r, -1, trace_id, parent_span,
                                          progress);
            total_resp_tokens += resp["completion_tokens"].as_int();
            std::lock_guard<std::mutex> g(mu);
            ready.push_back(resp.dump() + "\n");
            --remaining;
            cv.notify_all();
          });
      if (!ok) {  // pool stopped (shutdown): account the request as failed
        std::string rid = r["rid"].as_str();
        std::lock_guard<std::mutex> g(mu);
        ready.push_back(error_response(rid, "manager shutdown").dump() + "\n");
        --remaining;
        cv.notify_all();
      }
    }

    // drain results to the trainer as they finish
    {
      std::unique_lock<std::mutex> lk(mu);
      while (remaining > 0 || !ready.empty()) {
        cv.wait(lk, [&] { return !ready.empty() || remaining == 0; });
        while (!ready.empty()) {
          std::string line = std::move(ready.front());
          ready.pop_front();
          lk.unlock();
          rw.write_chunk(line);
          lk.lock();
        }
      }
    }
    batch_done = true;
    watchdog.join();

    double total_s = std::chrono::duration<double>(
                         std::chrono::steady_clock::now() - t_start).count();
    double mean_len = requests.empty() ? 0.0
                          : static_cast<double>(total_resp_tokens.load()) /
                                static_cast<double>(requests.size());
    state_.balance.record_generation(total_s, std::min(total_s, max_local_gen_s), mean_len);
  }

  // ---- background workers ---------------------------------------------

  // Stats poll doubles as the pool HEARTBEAT: every registered healthy
  // instance (not just the active routing set — drained/updating engines
  // still need death detection) is probed each tick. A poll answer resets
  // the miss counter and feeds the scheduler's load/version view; it also
  // carries the engine's own "draining" announcement (preemption notice →
  // out of the routing set before the next batch routes to it). A REMOTE
  // instance missing cfg.heartbeat_failures consecutive polls is EVICTED —
  // an engine that died WITHOUT notice; its in-flight rids fail their
  // streams and continue on survivors through the salvage path.
  void start_stats_poller() {
    stats_thread_ = std::thread([this] {
      while (!state_.is_shutdown()) {
        for (auto& inst : state_.all_instances()) {
          if (!inst->healthy.load()) continue;  // pending: own health check
          auto resp = phttp::request("GET", inst->endpoint, "/get_server_info", "", 2000);
          bool parsed = false;
          if (resp.ok()) {
            Value info = pjson::Parser::parse(resp.body, &parsed);
            if (parsed) {
              inst->heartbeat_misses = 0;
              inst->num_running_reqs = info["num_running_reqs"].as_int();
              inst->num_queued_reqs = info["num_queued_reqs"].as_int();
              inst->last_gen_throughput = info["last_gen_throughput"].as_num();
              // engine flight-deck forwarding: optional fields (absent on
              // pre-flight-deck engines) — only overwrite when reported
              auto fwd = [&](const char* key, std::atomic<double>& dst) {
                if (info[key].is_num()) dst = info[key].as_num();
              };
              fwd("occupancy", inst->occupancy);
              fwd("page_util", inst->page_util);
              fwd("ttft_p95_s", inst->ttft_p95_s);
              fwd("tpot_p95_s", inst->tpot_p95_s);
              fwd("prefix_cache/hit_rate", inst->cache_hit_rate);
              fwd("spec_accept_rate", inst->spec_accept_rate);
              fwd("attributed_frac", inst->attributed_frac);
              fwd("prefill_reuse_frac", inst->prefill_reuse_frac);
              fwd("prefix_hit_frac", inst->prefix_hit_frac);
              // KV memory plane: cold residency + HBM headroom. Absent on
              // ledger-off / CPU engines — headroom keeps its -1 sentinel
              fwd("kv_cold_page_frac", inst->kv_cold_page_frac);
              fwd("hbm_headroom_gb", inst->hbm_headroom_gb);
              // host-RAM spill tier: paged-out fraction + restore rate
              // (absent on spill-off engines — atomics keep their zeros)
              fwd("kv_spilled_frac", inst->kv_spilled_frac);
              fwd("kv_restore_rate", inst->kv_restore_rate);
              // engine-loop profiler: device-vs-host wall split (absent on
              // loop_profile-off engines — device_frac keeps its -1
              // sentinel)
              fwd("device_frac", inst->device_frac);
              fwd("accounting_frac", inst->accounting_frac);
              if (info["draining"].as_bool() && !inst->draining.load()) {
                log_line("instance " + inst->endpoint +
                         " announced draining; leaving routing set");
                state_.mark_draining(inst->endpoint);
              }
              // monotonic version raise from the engine's own report —
              // re-admits a caught-up engine the weight plane lost track of
              if (info["weight_version"].is_num())
                state_.set_instance_version(inst->endpoint,
                                            info["weight_version"].as_int());
            }
          }
          if (!parsed) {
            int64_t misses = inst->heartbeat_misses.fetch_add(1) + 1;
            if (cfg_.heartbeat_failures > 0 && !inst->is_local &&
                misses >= cfg_.heartbeat_failures) {
              log_line("evicting instance " + inst->endpoint + " after " +
                       std::to_string(misses) + " heartbeat misses");
              state_.evict(inst->endpoint);
            }
          }
        }
        state_.reset_quotas();
        std::this_thread::sleep_for(std::chrono::milliseconds(
            static_cast<int>(cfg_.stats_poll_interval_s * 1000)));
      }
    });
  }

  void health_check_async(const std::string& endpoint) {
    std::thread([this, endpoint] {
      auto deadline = std::chrono::steady_clock::now() +
                      std::chrono::duration<double>(cfg_.health_check_deadline_s);
      while (std::chrono::steady_clock::now() < deadline && !state_.is_shutdown()) {
        auto resp = phttp::request("GET", endpoint, "/health_generate", "", 3000);
        if (resp.ok()) {
          state_.promote_healthy(endpoint);
          log_line("instance healthy: " + endpoint);
          return;
        }
        std::this_thread::sleep_for(std::chrono::duration<double>(cfg_.health_check_interval_s));
      }
      log_line("health check deadline exceeded: " + endpoint);
      state_.deregister(endpoint);
    }).detach();
  }

  void join() {
    if (stats_thread_.joinable()) stats_thread_.join();
  }

  // ---- request accounting (per-route totals for /metrics) --------------

  void count_request(const std::string& path) {
    std::lock_guard<std::mutex> g(hits_mu_);
    ++route_hits_[path];
  }

  std::map<std::string, long> route_hits() {
    std::lock_guard<std::mutex> g(hits_mu_);
    return route_hits_;
  }

 private:
  Config cfg_;
  AppState state_;
  phttp::WorkerPool gen_pool_;
  std::thread stats_thread_;
  std::map<std::string, long> route_hits_;
  std::mutex hits_mu_;
};

// ---- route registration ----------------------------------------------------

void register_routes(phttp::Server& server, Manager& mgr) {
  auto& state = mgr.state();
  // sender/registration ACL (reference utils.rs:303-339): parsed once at
  // route setup; shared by value into the handlers (immutable after).
  const std::vector<Cidr> sender_acl = mgr.config().sender_acl();
  auto acl_reject = [sender_acl](const phttp::Request& req,
                                 phttp::ResponseWriter& rw) -> bool {
    if (ip_allowed(req.peer_ip, sender_acl)) return false;
    log_line("403 " + req.method + " " + req.path +
             " from disallowed ip " + req.peer_ip);
    rw.status = 403;
    rw.body = "{\"error\":\"sender ip not in allowed_sender_ips\"}";
    return true;
  };

  // request observer: per-route totals (exposed at /metrics) + trace-id
  // echo into the response headers + request log, so a trainer-side span
  // can be matched against the manager's own log without guessing.
  server.set_observer([&mgr](const phttp::Request& req,
                             phttp::ResponseWriter& rw) {
    mgr.count_request(req.path);
    std::string trace = header_of(req, "x-trace-id");
    if (trace.empty()) return;
    rw.extra_headers += "X-Trace-Id: " + trace + "\r\n";
    if (req.path == "/generate" || req.path == "/batch_generate_requests" ||
        req.path == "/update_weight_version")
      log_line(req.method + " " + req.path + " trace=" + trace);
  });

  server.route("GET", "/health", [](const phttp::Request&, phttp::ResponseWriter& rw) {
    rw.body = "{\"status\":\"ok\"}";
  });

  server.route("GET", "/get_instances_status",
               [&](const phttp::Request&, phttp::ResponseWriter& rw) {
    Array arr;
    for (auto& inst : state.all_instances()) {
      Object o;
      o["endpoint"] = Value(inst->endpoint);
      o["is_local"] = Value(inst->is_local);
      o["healthy"] = Value(inst->healthy.load());
      o["updating_weight"] = Value(inst->updating_weight.load());
      o["weight_version"] = Value(inst->weight_version.load());
      o["num_running_reqs"] = Value(inst->num_running_reqs.load());
      o["num_queued_reqs"] = Value(inst->num_queued_reqs.load());
      o["weight_sender"] = Value(inst->weight_sender);
      o["group_idx"] = Value(inst->group_idx);
      o["draining"] = Value(inst->draining.load());
      o["heartbeat_misses"] = Value(inst->heartbeat_misses.load());
      o["active"] = Value(state.is_active(inst->endpoint));
      o["last_gen_throughput"] = Value(inst->last_gen_throughput.load());
      o["occupancy"] = Value(inst->occupancy.load());
      o["page_util"] = Value(inst->page_util.load());
      o["ttft_p95_s"] = Value(inst->ttft_p95_s.load());
      o["tpot_p95_s"] = Value(inst->tpot_p95_s.load());
      o["cache_hit_rate"] = Value(inst->cache_hit_rate.load());
      o["spec_accept_rate"] = Value(inst->spec_accept_rate.load());
      o["attributed_frac"] = Value(inst->attributed_frac.load());
      o["prefill_reuse_frac"] = Value(inst->prefill_reuse_frac.load());
      o["prefix_hit_frac"] = Value(inst->prefix_hit_frac.load());
      o["kv_cold_page_frac"] = Value(inst->kv_cold_page_frac.load());
      // -1 sentinels "engine never reported headroom" (CPU / ledger off);
      // omitting the key keeps the fleet min from counting it as 0 GB
      if (inst->hbm_headroom_gb.load() >= 0.0)
        o["hbm_headroom_gb"] = Value(inst->hbm_headroom_gb.load());
      o["kv_spilled_frac"] = Value(inst->kv_spilled_frac.load());
      o["kv_restore_rate"] = Value(inst->kv_restore_rate.load());
      // -1 sentinels "engine never reported a loop profile" (loop_profile
      // off / pre-profiler); omitting the key keeps the fleet min honest
      if (inst->device_frac.load() >= 0.0) {
        o["device_frac"] = Value(inst->device_frac.load());
        o["accounting_frac"] = Value(inst->accounting_frac.load());
      }
      arr.push_back(Value(std::move(o)));
    }
    Object top;
    top["instances"] = Value(std::move(arr));
    top["weight_version"] = Value(state.weight_version());
    top["max_local_gen_s"] = Value(state.balance.max_local_gen_s());
    auto pc = state.pool_counts();
    Object pool;
    pool["joins"] = Value(pc.joins);
    pool["evictions"] = Value(pc.evictions);
    pool["drain_departures"] = Value(pc.drain_departures);
    pool["active"] = Value(pc.active);
    pool["pending"] = Value(pc.pending);
    pool["registered"] = Value(pc.registered);
    top["pool"] = Value(std::move(pool));
    rw.body = Value(std::move(top)).dump();
  });

  // Prometheus text exposition for ops scrapers: pool-level gauges plus
  // per-instance queue depths labeled by endpoint (the same data
  // /get_instances_status serves as JSON).
  server.route("GET", "/metrics",
               [&](const phttp::Request&, phttp::ResponseWriter& rw) {
    // label values per the Prometheus text format: escape \, " and
    // newline — endpoints arrive via the unauthenticated registration
    // route and must not be able to inject metric lines
    auto esc = [](const std::string& s) {
      std::string out;
      out.reserve(s.size());
      for (char c : s) {
        if (c == '\\') out += "\\\\";
        else if (c == '"') out += "\\\"";
        else if (c == '\n') out += "\\n";
        else out += c;
      }
      return out;
    };
    auto insts = state.all_instances();
    long healthy = 0, local_n = 0, running = 0, queued = 0;
    double occ_sum = 0.0, page_util_max = 0.0, tput_sum = 0.0;
    long occ_n = 0;
    std::string per;
    for (auto& inst : insts) {
      if (inst->healthy.load()) healthy++;
      if (inst->is_local) local_n++;
      long r = inst->num_running_reqs.load();
      long q = inst->num_queued_reqs.load();
      running += r;
      queued += q;
      per += "polyrl_mgr_instance_running_reqs{endpoint=\"" +
             esc(inst->endpoint) + "\"} " + std::to_string(r) + "\n";
      per += "polyrl_mgr_instance_queued_reqs{endpoint=\"" +
             esc(inst->endpoint) + "\"} " + std::to_string(q) + "\n";
      // engine flight-deck per-instance load view (the "why is decode
      // occupancy low on engine 3" answer, labeled by endpoint)
      per += "polyrl_mgr_instance_occupancy{endpoint=\"" +
             esc(inst->endpoint) + "\"} " +
             std::to_string(inst->occupancy.load()) + "\n";
      per += "polyrl_mgr_instance_page_util{endpoint=\"" +
             esc(inst->endpoint) + "\"} " +
             std::to_string(inst->page_util.load()) + "\n";
      per += "polyrl_mgr_instance_ttft_p95_s{endpoint=\"" +
             esc(inst->endpoint) + "\"} " +
             std::to_string(inst->ttft_p95_s.load()) + "\n";
      // KV memory plane per-instance view: which engine's resident set is
      // going cold, and who is closest to HBM exhaustion (-1 = unreported)
      per += "polyrl_mgr_instance_kv_cold_page_frac{endpoint=\"" +
             esc(inst->endpoint) + "\"} " +
             std::to_string(inst->kv_cold_page_frac.load()) + "\n";
      if (inst->hbm_headroom_gb.load() >= 0.0)
        per += "polyrl_mgr_instance_hbm_headroom_gb{endpoint=\"" +
               esc(inst->endpoint) + "\"} " +
               std::to_string(inst->hbm_headroom_gb.load()) + "\n";
      // host-RAM spill tier: who has KV paged out, and who is thrashing
      per += "polyrl_mgr_instance_kv_spilled_frac{endpoint=\"" +
             esc(inst->endpoint) + "\"} " +
             std::to_string(inst->kv_spilled_frac.load()) + "\n";
      per += "polyrl_mgr_instance_kv_restore_rate{endpoint=\"" +
             esc(inst->endpoint) + "\"} " +
             std::to_string(inst->kv_restore_rate.load()) + "\n";
      // engine-loop profiler: whose loop thread stopped feeding the chip,
      // and whose bookkeeping is eating the loop (-1 = unreported)
      if (inst->device_frac.load() >= 0.0) {
        per += "polyrl_mgr_instance_device_frac{endpoint=\"" +
               esc(inst->endpoint) + "\"} " +
               std::to_string(inst->device_frac.load()) + "\n";
        per += "polyrl_mgr_instance_accounting_frac{endpoint=\"" +
               esc(inst->endpoint) + "\"} " +
               std::to_string(inst->accounting_frac.load()) + "\n";
      }
      if (inst->healthy.load()) {
        occ_sum += inst->occupancy.load();
        ++occ_n;
        if (inst->page_util.load() > page_util_max)
          page_util_max = inst->page_util.load();
        tput_sum += inst->last_gen_throughput.load();
      }
    }
    std::string body;
    body += "# TYPE polyrl_mgr_instances gauge\npolyrl_mgr_instances " +
            std::to_string((long)insts.size()) + "\n";
    body += "# TYPE polyrl_mgr_instances_healthy gauge\n"
            "polyrl_mgr_instances_healthy " + std::to_string(healthy) + "\n";
    body += "# TYPE polyrl_mgr_instances_local gauge\n"
            "polyrl_mgr_instances_local " + std::to_string(local_n) + "\n";
    body += "# TYPE polyrl_mgr_weight_version counter\n"
            "polyrl_mgr_weight_version " +
            std::to_string(state.weight_version()) + "\n";
    body += "# TYPE polyrl_mgr_max_local_gen_s gauge\n"
            "polyrl_mgr_max_local_gen_s " +
            std::to_string(state.balance.max_local_gen_s()) + "\n";
    auto pc = state.pool_counts();
    body += "# TYPE polyrl_mgr_pool_joins counter\npolyrl_mgr_pool_joins " +
            std::to_string(pc.joins) + "\n";
    body += "# TYPE polyrl_mgr_pool_evictions counter\n"
            "polyrl_mgr_pool_evictions " + std::to_string(pc.evictions) + "\n";
    body += "# TYPE polyrl_mgr_pool_drain_departures counter\n"
            "polyrl_mgr_pool_drain_departures " +
            std::to_string(pc.drain_departures) + "\n";
    body += "# TYPE polyrl_mgr_pool_active gauge\npolyrl_mgr_pool_active " +
            std::to_string(pc.active) + "\n";
    body += "# TYPE polyrl_mgr_pool_pending gauge\npolyrl_mgr_pool_pending " +
            std::to_string(pc.pending) + "\n";
    body += "# TYPE polyrl_mgr_running_reqs gauge\npolyrl_mgr_running_reqs " +
            std::to_string(running) + "\n";
    body += "# TYPE polyrl_mgr_queued_reqs gauge\npolyrl_mgr_queued_reqs " +
            std::to_string(queued) + "\n";
    // fleet flight-deck aggregates: mean occupancy over healthy engines,
    // worst page-pool pressure, summed decode throughput
    body += "# TYPE polyrl_mgr_fleet_occupancy gauge\n"
            "polyrl_mgr_fleet_occupancy " +
            std::to_string(occ_n ? occ_sum / occ_n : 0.0) + "\n";
    body += "# TYPE polyrl_mgr_fleet_page_util gauge\n"
            "polyrl_mgr_fleet_page_util " + std::to_string(page_util_max) +
            "\n";
    body += "# TYPE polyrl_mgr_fleet_throughput_tok_s gauge\n"
            "polyrl_mgr_fleet_throughput_tok_s " + std::to_string(tput_sum) +
            "\n";
    body += "# TYPE polyrl_mgr_instance_running_reqs gauge\n";
    body += "# TYPE polyrl_mgr_instance_queued_reqs gauge\n";
    body += "# TYPE polyrl_mgr_instance_occupancy gauge\n";
    body += "# TYPE polyrl_mgr_instance_page_util gauge\n";
    body += "# TYPE polyrl_mgr_instance_ttft_p95_s gauge\n";
    body += "# TYPE polyrl_mgr_instance_kv_cold_page_frac gauge\n";
    body += "# TYPE polyrl_mgr_instance_hbm_headroom_gb gauge\n";
    body += "# TYPE polyrl_mgr_instance_kv_spilled_frac gauge\n";
    body += "# TYPE polyrl_mgr_instance_kv_restore_rate gauge\n";
    body += "# TYPE polyrl_mgr_instance_device_frac gauge\n";
    body += "# TYPE polyrl_mgr_instance_accounting_frac gauge\n";
    body += per;
    long total_reqs = 0;
    std::string per_route;
    for (const auto& kv : mgr.route_hits()) {
      total_reqs += kv.second;
      per_route += "polyrl_mgr_requests_total{path=\"" + esc(kv.first) +
                   "\"} " + std::to_string(kv.second) + "\n";
    }
    // unlabeled total: the trainer's per-step scrape merges only unlabeled
    // series into step records (obs/scrape.py)
    body += "# TYPE polyrl_mgr_requests counter\npolyrl_mgr_requests " +
            std::to_string(total_reqs) + "\n";
    body += "# TYPE polyrl_mgr_requests_total counter\n";
    body += per_route;
    rw.content_type = "text/plain; version=0.0.4";
    rw.body = body;
  });

  server.route("POST", "/register_rollout_instance",
               [&, acl_reject](const phttp::Request& req, phttp::ResponseWriter& rw) {
    if (acl_reject(req, rw)) return;
    Value body = pjson::Parser::parse(req.body);
    std::string endpoint = body["endpoint"].as_str();
    if (endpoint.empty()) { rw.status = 400; rw.body = "{\"error\":\"endpoint required\"}"; return; }
    auto [sender, group] = state.register_instance(endpoint, false);
    mgr.health_check_async(endpoint);
    Object o;
    o["weight_sender_endpoint"] = Value(sender);
    o["group_idx"] = Value(group);
    rw.body = Value(std::move(o)).dump();
    log_line("registered remote instance " + endpoint);
  });

  // Graceful leave (scale-down as a drill): the engine — or the pool
  // manager running a preemption drill — announces departure AFTER
  // draining. ``drained=true`` books it as a drain departure rather than
  // an eviction; idempotent (an already-forgotten endpoint is a no-op).
  server.route("POST", "/deregister_rollout_instance",
               [&, acl_reject](const phttp::Request& req, phttp::ResponseWriter& rw) {
    if (acl_reject(req, rw)) return;
    Value body = pjson::Parser::parse(req.body);
    std::string endpoint = body["endpoint"].as_str();
    if (endpoint.empty()) { rw.status = 400; rw.body = "{\"error\":\"endpoint required\"}"; return; }
    bool known = state.has_instance(endpoint);
    if (known) state.leave(endpoint, body["drained"].as_bool());
    Object o;
    o["status"] = Value("ok");
    o["removed"] = Value(known);
    rw.body = Value(std::move(o)).dump();
    log_line("deregistered instance " + endpoint +
             (body["drained"].as_bool() ? " (drained)" : ""));
  });

  server.route("POST", "/register_local_rollout_instances",
               [&, acl_reject](const phttp::Request& req, phttp::ResponseWriter& rw) {
    if (acl_reject(req, rw)) return;
    Value body = pjson::Parser::parse(req.body);
    for (const auto& ep : body["endpoints"].as_arr())
      state.register_instance(ep.as_str(), true);
    rw.body = "{\"status\":\"ok\"}";
  });

  // Idempotent bulk re-registration for supervisor replay after a respawn
  // (supervisor.py): already-known endpoints are left untouched (no
  // pending-state reset, no double health check), the weight version is
  // only ever RAISED (raise_weight_version_floor — no drain), and senders
  // are re-installed before instances so re-registrations get sender
  // assignments. Safe to call any number of times.
  server.route("POST", "/reconcile",
               [&, acl_reject](const phttp::Request& req, phttp::ResponseWriter& rw) {
    if (acl_reject(req, rw)) return;
    Value body = pjson::Parser::parse(req.body);
    if (body["senders"].is_arr() && !body["senders"].as_arr().empty()) {
      std::vector<std::string> senders;
      for (const auto& s : body["senders"].as_arr()) senders.push_back(s.as_str());
      int groups = static_cast<int>(body["groups_per_sender"].as_int(
          mgr.config().groups_per_sender));
      state.set_weight_senders(std::move(senders), groups);
    }
    int64_t version = state.raise_weight_version_floor(
        body["weight_version"].as_int(0));
    int64_t added_remote = 0, added_local = 0, kept = 0;
    for (const auto& epv : body["remote_endpoints"].as_arr()) {
      const std::string ep = epv.as_str();
      if (ep.empty()) continue;
      if (state.has_instance(ep)) { ++kept; continue; }
      state.register_instance(ep, false);
      mgr.health_check_async(ep);
      ++added_remote;
    }
    for (const auto& epv : body["local_endpoints"].as_arr()) {
      const std::string ep = epv.as_str();
      if (ep.empty()) continue;
      if (state.has_instance(ep)) { ++kept; continue; }
      state.register_instance(ep, true);
      ++added_local;
    }
    // pool-membership replay: each engine's last-known weight version.
    // Without this a respawned manager sees every replayed engine at -1,
    // gates the whole (healthy, caught-up) fleet behind a redundant weight
    // bootstrap, and orphans it if no sender ever re-pushes. Monotonic and
    // bootstrap-gated inside set_instance_version, so a double replay (or
    // a stale one) is a no-op.
    if (body["instance_versions"].is_obj()) {
      for (const auto& [ep, ver] : body["instance_versions"].as_obj())
        state.set_instance_version(ep, ver.as_int(-1));
    }
    Object o;
    o["status"] = Value("ok");
    o["added_remote"] = Value(added_remote);
    o["added_local"] = Value(added_local);
    o["kept"] = Value(kept);
    o["weight_version"] = Value(version);
    rw.body = Value(std::move(o)).dump();
    log_line("reconcile: +" + std::to_string(added_remote) + " remote, +" +
             std::to_string(added_local) + " local, " + std::to_string(kept) +
             " kept, weight_version " + std::to_string(version));
  });

  server.route("POST", "/generate",
               [&](const phttp::Request& req, phttp::ResponseWriter& rw) {
    Value body = pjson::Parser::parse(req.body);
    rw.body = mgr.process_generate(body, -1, header_of(req, "x-trace-id"),
                                   header_of(req, "x-span-id")).dump();
  });

  server.route("POST", "/batch_generate_requests",
               [&](const phttp::Request& req, phttp::ResponseWriter& rw) {
    Value body = pjson::Parser::parse(req.body);
    mgr.batch_generate(body, rw, header_of(req, "x-trace-id"),
                       header_of(req, "x-span-id"));
  });

  server.route("POST", "/update_weight_version",
               [&](const phttp::Request&, phttp::ResponseWriter& rw) {
    int64_t v = state.update_weight_version();
    Object o;
    o["weight_version"] = Value(v);
    rw.body = Value(std::move(o)).dump();
    log_line("weight version -> " + std::to_string(v));
  });

  server.route("POST", "/get_receive_instances",
               [&](const phttp::Request& req, phttp::ResponseWriter& rw) {
    Value body = pjson::Parser::parse(req.body);
    auto insts = state.get_receive_instances(body["sender"].as_str());
    Array arr;
    for (auto& inst : insts) {
      Object o;
      o["endpoint"] = Value(inst->endpoint);
      o["group_idx"] = Value(inst->group_idx);
      o["bootstrap"] = Value(inst->weight_version.load() < 0);
      arr.push_back(Value(std::move(o)));
    }
    Object top;
    top["instances"] = Value(std::move(arr));
    top["weight_version"] = Value(state.weight_version());
    rw.body = Value(std::move(top)).dump();
  });

  server.route("POST", "/update_weights",
               [&](const phttp::Request& req, phttp::ResponseWriter& rw) {
    // transfer complete for these instances: tell each engine to load from
    // its receiver agent, then rejoin the pool (handlers.rs:681-795)
    Value body = pjson::Parser::parse(req.body);
    int64_t version = body["weight_version"].is_num() ? body["weight_version"].as_int()
                                                      : state.weight_version();
    Array results;
    for (const auto& epv : body["instances"].as_arr()) {
      std::string ep = epv.as_str();
      Object per;
      per["endpoint"] = Value(ep);
      auto resp = phttp::request("POST", ep, "/update_weights_from_agent",
                                 "{\"weight_version\":" + std::to_string(version) + "}",
                                 120000);
      if (resp.ok()) {
        state.complete_weight_update(ep, version);
        per["success"] = Value(true);
      } else {
        state.abort_weight_update(ep);
        per["success"] = Value(false);
      }
      results.push_back(Value(std::move(per)));
    }
    Object top;
    top["results"] = Value(std::move(results));
    rw.body = Value(std::move(top)).dump();
  });

  server.route("POST", "/abort_weight_update",
               [&](const phttp::Request& req, phttp::ResponseWriter& rw) {
    // sender-side push failed (receiver missing / TCP error): clear the
    // updating_weight CAS so the instance is retried on the next sender
    // poll instead of being drained forever
    Value body = pjson::Parser::parse(req.body);
    for (const auto& epv : body["instances"].as_arr())
      state.abort_weight_update(epv.as_str());
    rw.body = "{\"status\":\"ok\"}";
  });

  server.route("PUT", "/update_weight_senders",
               [&, acl_reject](const phttp::Request& req, phttp::ResponseWriter& rw) {
    if (acl_reject(req, rw)) return;
    Value body = pjson::Parser::parse(req.body);
    std::vector<std::string> senders;
    for (const auto& s : body["senders"].as_arr()) senders.push_back(s.as_str());
    int groups = static_cast<int>(body["groups_per_sender"].as_int(mgr.config().groups_per_sender));
    state.set_weight_senders(std::move(senders), groups);
    rw.body = "{\"status\":\"ok\"}";
  });

  server.route("POST", "/shutdown_instances",
               [&](const phttp::Request& req, phttp::ResponseWriter& rw) {
    Value body = pjson::Parser::parse(req.body);
    bool skip_updating = body["skip_if_updating_weights"].as_bool();
    int count = 0;
    for (auto& inst : state.all_instances()) {
      if (inst->is_local) continue;
      if (skip_updating && inst->updating_weight.load()) continue;
      phttp::request("POST", inst->endpoint, "/shutdown", "{}", 2000);
      state.deregister(inst->endpoint);
      ++count;
    }
    Object o;
    o["shutdown_count"] = Value(count);
    rw.body = Value(std::move(o)).dump();
  });

  server.route("POST", "/update_metrics",
               [&](const phttp::Request& req, phttp::ResponseWriter& rw) {
    Value body = pjson::Parser::parse(req.body);
    LoadBalanceState::StepStats s;
    s.step_time_s = body["step_time_s"].as_num();
    s.total_gen_time_s = body["total_gen_time_s"].is_num()
                             ? body["total_gen_time_s"].as_num()
                             : state.balance.last_total_gen_s();
    s.trainer_bubble_s = body["trainer_bubble_s"].as_num();
    s.throughput = body["throughput"].as_num();
    s.num_instances = static_cast<int>(body["num_instances"].as_int(
        static_cast<int64_t>(state.active_count())));
    double new_window = state.balance.update(s);
    Object o;
    o["max_local_gen_s"] = Value(new_window);
    o["num_instances"] = Value(static_cast<int64_t>(state.active_count()));
    rw.body = Value(std::move(o)).dump();
  });

  server.route("POST", "/abort_local_requests",
               [&](const phttp::Request&, phttp::ResponseWriter& rw) {
    auto locals = state.remove_local_from_active();
    for (auto& inst : locals)
      phttp::request("POST", inst->endpoint, "/abort_request", "{\"abort_all\":true}", 2000);
    Object o;
    o["aborted_instances"] = Value(static_cast<int64_t>(locals.size()));
    rw.body = Value(std::move(o)).dump();
  });

  server.route("POST", "/resume_local_instances",
               [&](const phttp::Request&, phttp::ResponseWriter& rw) {
    state.add_local_to_active();
    rw.body = "{\"status\":\"ok\"}";
  });
}

}  // namespace manager

int main(int argc, char** argv) {
  signal(SIGPIPE, SIG_IGN);
  manager::Config cfg;
  try {
    cfg = manager::load_config(argc, argv);
  } catch (const std::exception& e) {
    fprintf(stderr, "bad config: %s\n", e.what());
    return 1;
  }
  manager::Manager mgr(cfg);
  phttp::Server server(static_cast<size_t>(std::max(cfg.http_workers, 1)));
  manager::register_routes(server, mgr);

  std::string host;
  int port;
  if (!phttp::split_endpoint(cfg.bind_addr, host, port)) {
    fprintf(stderr, "bad --bind-addr %s\n", cfg.bind_addr.c_str());
    return 1;
  }
  int bound = server.listen(host, port);
  if (bound < 0) {
    fprintf(stderr, "failed to bind %s\n", cfg.bind_addr.c_str());
    return 1;
  }
  manager::log_line("listening on " + host + ":" + std::to_string(bound));
  printf("LISTENING %d\n", bound);
  fflush(stdout);
  mgr.start_stats_poller();
  server.serve();
  return 0;
}
