// Adaptive local/remote workload balancer.
//
// C++ equivalent of the reference's balance.rs (SURVEY.md C16c): a
// hill-climbing controller for the colocated engines' generation window
// (max_local_instance_gen_s). Inputs per step: total gen time, step time,
// trainer bubble (trainer idle waiting on rollout), instance count,
// throughput. Rule (balance.rs:193-205): remote_bubble = step_time -
// total_gen_time; trainer bubble < remote bubble → shrink local gen by
// gap/3 (floor 5 s), else grow by gap/3. A per-instance-count optimal
// table is remembered with EMA (α on throughput-drop, β on count change,
// balance.rs:105-155) and reused instantly when the count changes.
//
// The hardcoded GPU seed tables (8B: {1:190, 2:160, 3:105, 4:70}) are NOT
// ported — they are hardware-specific tuning; the TPU build starts from
// the initial window and learns.
#pragma once

#include <cmath>
#include <cstdint>
#include <map>
#include <mutex>

namespace manager {

class LoadBalanceState {
 public:
  static constexpr double kAlpha = 0.8;   // EMA on throughput drop
  static constexpr double kBeta = 0.2;    // EMA on instance-count change
  static constexpr double kMinGenS = 5.0;
  static constexpr double kInitialGenS = 150.0;

  struct StepStats {
    double step_time_s = 0;
    double total_gen_time_s = 0;
    double local_gen_time_s = 0;
    double trainer_bubble_s = 0;
    double throughput = 0;       // tok/s (or any monotone proxy)
    int num_instances = 0;
  };

  double max_local_gen_s() {
    std::lock_guard<std::mutex> g(mu_);
    return max_local_gen_s_;
  }

  void set_initial_gen_s(double v) {
    std::lock_guard<std::mutex> g(mu_);
    max_local_gen_s_ = std::max(v, kMinGenS);
  }

  // Per-step update; returns the new local-generation window.
  double update(const StepStats& s) {
    std::lock_guard<std::mutex> g(mu_);
    // instance count changed: recall the remembered optimum for this count
    if (s.num_instances != last_instances_ && last_instances_ >= 0) {
      remember_locked(last_instances_, max_local_gen_s_, kBeta);
      auto it = optimal_.find(s.num_instances);
      if (it != optimal_.end()) max_local_gen_s_ = it->second;
    }
    last_instances_ = s.num_instances;

    // throughput-peak tracking: a significant drop pulls the window back
    // toward the best-seen value for this count (balance.rs:156-191).
    if (s.throughput > peak_throughput_) {
      peak_throughput_ = s.throughput;
      best_gen_s_ = max_local_gen_s_;
    } else if (peak_throughput_ > 0 &&
               s.throughput < 0.9 * peak_throughput_ && best_gen_s_ > 0) {
      max_local_gen_s_ = kAlpha * best_gen_s_ + (1 - kAlpha) * max_local_gen_s_;
    }

    // hill climb on the bubble gap
    double remote_bubble = s.step_time_s - s.total_gen_time_s;
    double gap = std::fabs(s.trainer_bubble_s - remote_bubble);
    if (s.trainer_bubble_s < remote_bubble) {
      max_local_gen_s_ -= gap / 3.0;
    } else {
      max_local_gen_s_ += gap / 3.0;
    }
    if (max_local_gen_s_ < kMinGenS) max_local_gen_s_ = kMinGenS;
    remember_locked(s.num_instances, max_local_gen_s_, kBeta);
    return max_local_gen_s_;
  }

  void record_generation(double total_gen_s, double local_gen_s, double mean_resp_len) {
    std::lock_guard<std::mutex> g(mu_);
    last_total_gen_s_ = total_gen_s;
    last_local_gen_s_ = local_gen_s;
    mean_response_len_ = mean_resp_len;
  }

  double last_total_gen_s() {
    std::lock_guard<std::mutex> g(mu_);
    return last_total_gen_s_;
  }
  double mean_response_len() {
    std::lock_guard<std::mutex> g(mu_);
    return mean_response_len_;
  }

  std::map<int, double> optimal_table() {
    std::lock_guard<std::mutex> g(mu_);
    return optimal_;
  }

 private:
  void remember_locked(int count, double value, double ema) {
    auto it = optimal_.find(count);
    if (it == optimal_.end()) {
      optimal_[count] = value;
    } else {
      it->second = ema * value + (1 - ema) * it->second;
    }
  }

  std::mutex mu_;
  double max_local_gen_s_ = kInitialGenS;
  int last_instances_ = -1;
  double peak_throughput_ = 0;
  double best_gen_s_ = -1;
  std::map<int, double> optimal_;
  double last_total_gen_s_ = 0;
  double last_local_gen_s_ = 0;
  double mean_response_len_ = 0;
};

}  // namespace manager
