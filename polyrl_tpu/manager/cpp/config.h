// CLI + TOML-subset config (override order: CLI > file > default),
// mirroring the reference's config plane (SURVEY.md C16f, config.rs:6).
#pragma once

#include <arpa/inet.h>

#include <cstdint>
#include <fstream>
#include <map>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace manager {

// IPv4 CIDR filter for the sender/registration ACL (the reference enforces
// allowed_sender_ips on both sides, utils.rs:303-339). A bare IP parses as
// /32.
struct Cidr {
  uint32_t addr = 0;  // host byte order
  uint32_t mask = 0;

  bool contains(uint32_t ip) const { return (ip & mask) == (addr & mask); }
};

inline bool parse_ipv4(const std::string& s, uint32_t& out) {
  in_addr a{};
  if (inet_pton(AF_INET, s.c_str(), &a) != 1) return false;
  out = ntohl(a.s_addr);
  return true;
}

inline Cidr parse_cidr(const std::string& spec) {
  Cidr c;
  size_t slash = spec.find('/');
  std::string ip = slash == std::string::npos ? spec : spec.substr(0, slash);
  int bits = 32;
  if (slash != std::string::npos) {
    bits = std::stoi(spec.substr(slash + 1));
    if (bits < 0 || bits > 32) throw std::invalid_argument("bad CIDR " + spec);
  }
  if (!parse_ipv4(ip, c.addr)) throw std::invalid_argument("bad CIDR " + spec);
  c.mask = bits == 0 ? 0 : (~0u << (32 - bits));
  return c;
}

// empty allowlist = open (matches the reference default: the field is
// opt-in); otherwise the peer IP must fall inside one of the CIDRs.
inline bool ip_allowed(const std::string& peer_ip,
                       const std::vector<Cidr>& allow) {
  if (allow.empty()) return true;
  uint32_t ip = 0;
  if (!parse_ipv4(peer_ip, ip)) return false;
  for (const auto& c : allow)
    if (c.contains(ip)) return true;
  return false;
}

// `["a", "b"]` or bare `a,b` → vector of trimmed strings.
inline std::vector<std::string> parse_string_list(std::string v) {
  std::vector<std::string> out;
  if (!v.empty() && v.front() == '[' && v.back() == ']')
    v = v.substr(1, v.size() - 2);
  std::stringstream ss(v);
  std::string item;
  while (std::getline(ss, item, ',')) {
    size_t a = item.find_first_not_of(" \t\"'");
    size_t b = item.find_last_not_of(" \t\"'");
    if (a != std::string::npos) out.push_back(item.substr(a, b - a + 1));
  }
  return out;
}

struct Config {
  std::string bind_addr = "0.0.0.0:30000";
  int max_assigned_batches_per_stats_check = 4;
  double stats_poll_interval_s = 1.0;
  double health_check_interval_s = 2.0;
  double health_check_deadline_s = 300.0;
  // elastic pool: consecutive stats-poll misses before a REMOTE instance
  // is evicted (heartbeat-timeout death detection; locals are exempt —
  // they fail by time-slice abort, not by dying). 0 disables eviction.
  int heartbeat_failures = 3;
  int max_generate_attempts = 5;
  int generate_timeout_ms = 600000;
  int schedule_wait_timeout_ms = 120000;  // block on instance availability
  int groups_per_sender = 4;
  double initial_local_gen_s = 150.0;
  // bounded concurrency (reference: tokio runtime; round-1 finding):
  // connection workers serve HTTP (streaming batches hold one each);
  // generate workers bound concurrent per-request engine streams.
  int http_workers = 64;
  int generate_workers = 128;
  // CIDR allowlist enforced on PUT /update_weight_senders and instance
  // registration (empty = open; reference utils.rs:303-339)
  std::vector<std::string> allowed_sender_ips;

  std::vector<Cidr> sender_acl() const {
    std::vector<Cidr> out;
    for (const auto& s : allowed_sender_ips) out.push_back(parse_cidr(s));
    return out;
  }
};

// Minimal TOML subset: `key = value` lines; strings, ints, floats, bools,
// arrays of strings; [sections] flattened as "section.key".
inline std::map<std::string, std::string> parse_toml(const std::string& path) {
  std::map<std::string, std::string> out;
  std::ifstream f(path);
  std::string line, section;
  while (std::getline(f, line)) {
    size_t hash = line.find('#');
    if (hash != std::string::npos) line = line.substr(0, hash);
    auto trim = [](std::string s) {
      size_t a = s.find_first_not_of(" \t\r");
      size_t b = s.find_last_not_of(" \t\r");
      return a == std::string::npos ? std::string() : s.substr(a, b - a + 1);
    };
    line = trim(line);
    if (line.empty()) continue;
    if (line.front() == '[' && line.back() == ']') {
      section = trim(line.substr(1, line.size() - 2));
      continue;
    }
    size_t eq = line.find('=');
    if (eq == std::string::npos) continue;
    std::string key = trim(line.substr(0, eq));
    std::string val = trim(line.substr(eq + 1));
    if (val.size() >= 2 && val.front() == '"' && val.back() == '"')
      val = val.substr(1, val.size() - 2);
    out[(section.empty() ? key : section + "." + key)] = val;
  }
  return out;
}

inline Config load_config(int argc, char** argv) {
  Config cfg;
  std::string config_file;
  // pass 1: find --config-file
  for (int i = 1; i < argc - 1; ++i)
    if (std::string(argv[i]) == "--config-file") config_file = argv[i + 1];
  if (!config_file.empty()) {
    auto kv = parse_toml(config_file);
    auto get = [&](const std::string& k) -> const std::string* {
      auto it = kv.find(k);
      return it == kv.end() ? nullptr : &it->second;
    };
    if (auto* v = get("bind_addr")) cfg.bind_addr = *v;
    if (auto* v = get("max_assigned_batches_per_stats_check"))
      cfg.max_assigned_batches_per_stats_check = std::stoi(*v);
    if (auto* v = get("stats_poll_interval_s")) cfg.stats_poll_interval_s = std::stod(*v);
    if (auto* v = get("health_check_interval_s")) cfg.health_check_interval_s = std::stod(*v);
    if (auto* v = get("health_check_deadline_s")) cfg.health_check_deadline_s = std::stod(*v);
    if (auto* v = get("heartbeat_failures")) cfg.heartbeat_failures = std::stoi(*v);
    if (auto* v = get("max_generate_attempts")) cfg.max_generate_attempts = std::stoi(*v);
    if (auto* v = get("generate_timeout_ms")) cfg.generate_timeout_ms = std::stoi(*v);
    if (auto* v = get("schedule_wait_timeout_ms")) cfg.schedule_wait_timeout_ms = std::stoi(*v);
    if (auto* v = get("groups_per_sender")) cfg.groups_per_sender = std::stoi(*v);
    if (auto* v = get("initial_local_gen_s")) cfg.initial_local_gen_s = std::stod(*v);
    if (auto* v = get("http_workers")) cfg.http_workers = std::stoi(*v);
    if (auto* v = get("generate_workers")) cfg.generate_workers = std::stoi(*v);
    if (auto* v = get("allowed_sender_ips"))
      cfg.allowed_sender_ips = parse_string_list(*v);
  }
  // pass 2: CLI overrides
  for (int i = 1; i < argc - 1; ++i) {
    std::string a = argv[i];
    std::string v = argv[i + 1];
    if (a == "--bind-addr") cfg.bind_addr = v;
    else if (a == "--max-assigned-batches") cfg.max_assigned_batches_per_stats_check = std::stoi(v);
    else if (a == "--stats-poll-interval-s") cfg.stats_poll_interval_s = std::stod(v);
    else if (a == "--health-check-interval-s") cfg.health_check_interval_s = std::stod(v);
    else if (a == "--health-check-deadline-s") cfg.health_check_deadline_s = std::stod(v);
    else if (a == "--heartbeat-failures") cfg.heartbeat_failures = std::stoi(v);
    else if (a == "--max-generate-attempts") cfg.max_generate_attempts = std::stoi(v);
    else if (a == "--generate-timeout-ms") cfg.generate_timeout_ms = std::stoi(v);
    else if (a == "--schedule-wait-timeout-ms") cfg.schedule_wait_timeout_ms = std::stoi(v);
    else if (a == "--groups-per-sender") cfg.groups_per_sender = std::stoi(v);
    else if (a == "--initial-local-gen-s") cfg.initial_local_gen_s = std::stod(v);
    else if (a == "--http-workers") cfg.http_workers = std::stoi(v);
    else if (a == "--generate-workers") cfg.generate_workers = std::stoi(v);
    else if (a == "--allowed-sender-ips")
      cfg.allowed_sender_ips = parse_string_list(v);
  }
  cfg.sender_acl();  // fail fast on malformed CIDRs at startup, not first use
  return cfg;
}

}  // namespace manager
