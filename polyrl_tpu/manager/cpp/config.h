// CLI + TOML-subset config (override order: CLI > file > default),
// mirroring the reference's config plane (SURVEY.md C16f, config.rs:6).
#pragma once

#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

namespace manager {

struct Config {
  std::string bind_addr = "0.0.0.0:30000";
  int max_assigned_batches_per_stats_check = 4;
  double stats_poll_interval_s = 1.0;
  double health_check_interval_s = 2.0;
  double health_check_deadline_s = 300.0;
  int max_generate_attempts = 5;
  int generate_timeout_ms = 600000;
  int schedule_wait_timeout_ms = 120000;  // block on instance availability
  int groups_per_sender = 4;
  double initial_local_gen_s = 150.0;
  // bounded concurrency (reference: tokio runtime; round-1 finding):
  // connection workers serve HTTP (streaming batches hold one each);
  // generate workers bound concurrent per-request engine streams.
  int http_workers = 64;
  int generate_workers = 128;
  std::vector<std::string> allowed_sender_ips;  // CIDR filters (doc only v0)
};

// Minimal TOML subset: `key = value` lines; strings, ints, floats, bools,
// arrays of strings; [sections] flattened as "section.key".
inline std::map<std::string, std::string> parse_toml(const std::string& path) {
  std::map<std::string, std::string> out;
  std::ifstream f(path);
  std::string line, section;
  while (std::getline(f, line)) {
    size_t hash = line.find('#');
    if (hash != std::string::npos) line = line.substr(0, hash);
    auto trim = [](std::string s) {
      size_t a = s.find_first_not_of(" \t\r");
      size_t b = s.find_last_not_of(" \t\r");
      return a == std::string::npos ? std::string() : s.substr(a, b - a + 1);
    };
    line = trim(line);
    if (line.empty()) continue;
    if (line.front() == '[' && line.back() == ']') {
      section = trim(line.substr(1, line.size() - 2));
      continue;
    }
    size_t eq = line.find('=');
    if (eq == std::string::npos) continue;
    std::string key = trim(line.substr(0, eq));
    std::string val = trim(line.substr(eq + 1));
    if (val.size() >= 2 && val.front() == '"' && val.back() == '"')
      val = val.substr(1, val.size() - 2);
    out[(section.empty() ? key : section + "." + key)] = val;
  }
  return out;
}

inline Config load_config(int argc, char** argv) {
  Config cfg;
  std::string config_file;
  // pass 1: find --config-file
  for (int i = 1; i < argc - 1; ++i)
    if (std::string(argv[i]) == "--config-file") config_file = argv[i + 1];
  if (!config_file.empty()) {
    auto kv = parse_toml(config_file);
    auto get = [&](const std::string& k) -> const std::string* {
      auto it = kv.find(k);
      return it == kv.end() ? nullptr : &it->second;
    };
    if (auto* v = get("bind_addr")) cfg.bind_addr = *v;
    if (auto* v = get("max_assigned_batches_per_stats_check"))
      cfg.max_assigned_batches_per_stats_check = std::stoi(*v);
    if (auto* v = get("stats_poll_interval_s")) cfg.stats_poll_interval_s = std::stod(*v);
    if (auto* v = get("health_check_interval_s")) cfg.health_check_interval_s = std::stod(*v);
    if (auto* v = get("health_check_deadline_s")) cfg.health_check_deadline_s = std::stod(*v);
    if (auto* v = get("max_generate_attempts")) cfg.max_generate_attempts = std::stoi(*v);
    if (auto* v = get("generate_timeout_ms")) cfg.generate_timeout_ms = std::stoi(*v);
    if (auto* v = get("schedule_wait_timeout_ms")) cfg.schedule_wait_timeout_ms = std::stoi(*v);
    if (auto* v = get("groups_per_sender")) cfg.groups_per_sender = std::stoi(*v);
    if (auto* v = get("initial_local_gen_s")) cfg.initial_local_gen_s = std::stod(*v);
    if (auto* v = get("http_workers")) cfg.http_workers = std::stoi(*v);
    if (auto* v = get("generate_workers")) cfg.generate_workers = std::stoi(*v);
  }
  // pass 2: CLI overrides
  for (int i = 1; i < argc - 1; ++i) {
    std::string a = argv[i];
    std::string v = argv[i + 1];
    if (a == "--bind-addr") cfg.bind_addr = v;
    else if (a == "--max-assigned-batches") cfg.max_assigned_batches_per_stats_check = std::stoi(v);
    else if (a == "--stats-poll-interval-s") cfg.stats_poll_interval_s = std::stod(v);
    else if (a == "--health-check-interval-s") cfg.health_check_interval_s = std::stod(v);
    else if (a == "--health-check-deadline-s") cfg.health_check_deadline_s = std::stod(v);
    else if (a == "--max-generate-attempts") cfg.max_generate_attempts = std::stoi(v);
    else if (a == "--generate-timeout-ms") cfg.generate_timeout_ms = std::stoi(v);
    else if (a == "--schedule-wait-timeout-ms") cfg.schedule_wait_timeout_ms = std::stoi(v);
    else if (a == "--groups-per-sender") cfg.groups_per_sender = std::stoi(v);
    else if (a == "--initial-local-gen-s") cfg.initial_local_gen_s = std::stod(v);
    else if (a == "--http-workers") cfg.http_workers = std::stoi(v);
    else if (a == "--generate-workers") cfg.generate_workers = std::stoi(v);
  }
  return cfg;
}

}  // namespace manager
