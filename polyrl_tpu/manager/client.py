"""ManagerClient + launcher — Python side of the rollout control plane.

Plays the roles of the reference's trainer-side HTTP calls
(``stream_batch_iter.py`` streaming batch iterator, C7;
``launcher.py:32-49`` spawn_rollout_manager; registration/metrics calls in
``stream_ray_trainer.py:691-704`` and ``sglang_http_async_engine.py:102-113``)
against the C++ ``polyrl-manager`` binary.

Fault tolerance (control-plane tier, ARCHITECTURE.md "Fault-tolerance
layers"): idempotent JSON calls retry with capped exponential backoff +
jitter on transport errors and 5xx responses; non-idempotent calls fail
fast with a typed :class:`ManagerTransportError` so the caller decides
(re-running ``/generate`` or a version bump is not safe to do blindly).
When the client is bound to a :class:`~polyrl_tpu.manager.supervisor.
ManagerSupervisor`, the endpoint re-resolves through it on every attempt —
a respawned manager binds a fresh ephemeral port and the next retry simply
lands there.
"""

from __future__ import annotations

import http.client
import json
import os
import random
import socket
import subprocess
import tempfile
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Iterator

from polyrl_tpu import obs

_CPP_DIR = os.path.join(os.path.dirname(__file__), "cpp")
_BINARY = os.path.join(_CPP_DIR, "polyrl-manager")


class ManagerError(RuntimeError):
    """Base class for control-plane client errors."""


class ManagerTransportError(ManagerError):
    """The manager could not be reached (connection error / timeout /
    truncated response). Raised immediately for non-idempotent calls and
    after the retry budget for idempotent ones."""


class ControlPlaneDown(ManagerError):
    """The manager stayed unreachable past the stream resume budget and no
    local fallback could finish the batch (rollout/remote.py)."""


def build_manager(force: bool = False) -> str:
    """(Re)build the C++ manager; returns the binary path. Always runs
    ``make`` — its dependency check is a no-op when the binary is fresh,
    and a checked-in binary must not shadow newer sources."""
    try:
        subprocess.run(["make", "-C", _CPP_DIR], check=True,
                       capture_output=True)
    except (OSError, subprocess.CalledProcessError):
        # no toolchain on this box: fall back to a prebuilt binary
        if not force and os.path.exists(_BINARY):
            return _BINARY
        raise
    return _BINARY


def spawn_rollout_manager(bind_addr: str = "0.0.0.0:0",
                          config_file: str | None = None,
                          extra_args: list[str] | None = None,
                          log_path: str | None = None):
    """Start the manager subprocess; returns (Popen, port). Reads the
    'LISTENING <port>' line the binary prints (supports ephemeral ports).

    stderr (the manager's own log lines) is teed to ``log_path`` — default
    a per-spawn file under the temp dir — so chaos-test and CI failures are
    debuggable instead of vanishing into DEVNULL. The path is recorded on
    the returned Popen as ``manager_log_path``."""
    binary = build_manager()
    cmd = [binary, "--bind-addr", bind_addr]
    if config_file:
        cmd += ["--config-file", config_file]
    cmd += extra_args or []
    if log_path is None:
        log_path = os.path.join(
            tempfile.gettempdir(),
            f"polyrl-manager-{os.getpid()}-{time.monotonic_ns()}.log")
    log_f = open(log_path, "ab")
    try:
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=log_f,
                                text=True)
    finally:
        log_f.close()  # the child inherited the fd
    proc.manager_log_path = log_path
    line = proc.stdout.readline().strip()
    if not line.startswith("LISTENING"):
        proc.kill()
        tail = ""
        try:
            with open(log_path, "rb") as f:
                tail = f.read()[-2048:].decode(errors="replace").strip()
        except OSError:
            pass
        raise RuntimeError(
            f"manager failed to start: {line!r} (log {log_path}): {tail}")
    port = int(line.split()[1])
    return proc, port


@dataclass
class GenerateResult:
    rid: str
    success: bool
    output_token_ids: list[int]
    output_token_logprobs: list[float]
    finish_reason: str
    error: str = ""
    # per-token engine weight version (token-level continuation: a resume
    # stitched across a weight push carries tokens sampled under different
    # policies). Empty when the manager/engine predates the field; -1 for
    # tokens whose engine did not report one.
    output_token_weight_versions: list[int] = field(default_factory=list)


@dataclass
class GenerateProgress:
    """One token-level progress chunk forwarded by the manager mid-stream
    (``{"type":"progress"}`` NDJSON lines): the salvage ledger's feed.
    Tokens reported here are NOT final — the terminal
    :class:`GenerateResult` for the rid repeats them authoritatively."""
    rid: str
    token_ids: list[int]
    logprobs: list[float]
    weight_version: int = -1


# transport-level failures worth retrying (connection refused/reset,
# timeouts, truncated chunked bodies). urllib.error.HTTPError subclasses
# URLError and must be handled FIRST (it is a status, not a transport fault).
_TRANSPORT_ERRORS = (urllib.error.URLError, http.client.HTTPException,
                     ConnectionError, TimeoutError, socket.timeout, OSError)


class ManagerClient:
    def __init__(self, endpoint: str = "", timeout_s: float = 600.0,
                 supervisor=None, retry_deadline_s: float = 30.0,
                 max_retries: int = 8, backoff_base_s: float = 0.2,
                 backoff_max_s: float = 2.0):
        if not endpoint and supervisor is None:
            raise ValueError("ManagerClient needs an endpoint or a supervisor")
        self._endpoint = (endpoint if not endpoint or endpoint.startswith("http")
                          else f"http://{endpoint}")
        self.supervisor = supervisor
        self.timeout_s = timeout_s
        self.retry_deadline_s = retry_deadline_s
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.retry_count = 0  # cumulative, surfaced as fault/client_retries

    @property
    def endpoint(self) -> str:
        """Current manager base URL; re-resolves through the supervisor (a
        respawned manager binds a fresh ephemeral port)."""
        if self.supervisor is not None:
            ep = self.supervisor.endpoint
            if ep:
                return ep if ep.startswith("http") else f"http://{ep}"
        return self._endpoint

    # -- plain JSON calls --------------------------------------------------

    def _call_once(self, method: str, path: str, payload: dict | None = None,
                   timeout: float | None = None) -> dict:
        data = json.dumps(payload or {}).encode()
        headers = {"Content-Type": "application/json"}
        # cross-process trace propagation: the manager echoes the pair in
        # its request log/response and forwards it to the engines it routes
        # to, so one request is followable trainer→manager→engine
        headers.update(obs.trace_headers())
        req = urllib.request.Request(
            self.endpoint + path, data=data, method=method, headers=headers)
        t0 = time.monotonic()
        with urllib.request.urlopen(req, timeout=timeout or self.timeout_s) as r:
            out = json.loads(r.read() or b"{}")
        obs.observe("manager/rtt_s", time.monotonic() - t0)
        return out

    def _call(self, method: str, path: str, payload: dict | None = None,
              timeout: float | None = None, idempotent: bool = False) -> dict:
        with obs.span("manager" + path):
            return self._call_retrying(method, path, payload, timeout,
                                       idempotent)

    def _call_retrying(self, method: str, path: str,
                       payload: dict | None = None,
                       timeout: float | None = None,
                       idempotent: bool = False) -> dict:
        attempt = 0
        deadline = time.monotonic() + self.retry_deadline_s
        while True:
            try:
                return self._call_once(method, path, payload, timeout)
            except urllib.error.HTTPError as exc:
                # status errors (4xx: bad request / ACL 403) are the
                # caller's problem; only a 5xx on an idempotent call retries
                if not idempotent or exc.code < 500:
                    raise
                err: Exception = exc
            except _TRANSPORT_ERRORS as exc:
                if not idempotent:
                    raise ManagerTransportError(
                        f"{method} {path} failed: {exc}") from exc
                err = exc
            attempt += 1
            self.retry_count += 1
            left = deadline - time.monotonic()
            if attempt > self.max_retries or left <= 0:
                raise ManagerTransportError(
                    f"{method} {path} failed after {attempt} attempts: "
                    f"{err}") from err
            # capped exponential backoff with jitter in [0.5x, 1.5x]
            sleep = min(self.backoff_base_s * 2 ** (attempt - 1),
                        self.backoff_max_s) * (0.5 + random.random())
            time.sleep(min(sleep, max(left, 0.0)))

    def health(self) -> bool:
        # single probe, no internal retry: wait_healthy/supervisor loops own
        # the retry cadence and want a fast, honest answer
        try:
            return self._call_once("GET", "/health",
                                   timeout=3.0).get("status") == "ok"
        except Exception:
            return False

    def wait_healthy(self, deadline_s: float = 30.0) -> None:
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            if self.health():
                return
            time.sleep(0.1)
        raise TimeoutError("manager not healthy")

    def get_instances_status(self) -> dict:
        return self._call("GET", "/get_instances_status", idempotent=True)

    def register_rollout_instance(self, instance_endpoint: str) -> dict:
        out = self._call("POST", "/register_rollout_instance",
                         {"endpoint": instance_endpoint}, idempotent=True)
        if self.supervisor is not None:
            self.supervisor.record_remote_instances([instance_endpoint])
        return out

    def register_local_rollout_instances(self, endpoints: list[str]) -> dict:
        out = self._call("POST", "/register_local_rollout_instances",
                         {"endpoints": endpoints}, idempotent=True)
        if self.supervisor is not None:
            self.supervisor.record_local_instances(endpoints)
        return out

    def deregister_rollout_instance(self, endpoint: str,
                                    drained: bool = False) -> dict:
        """Graceful leave (scale-down drill): remove one engine from the
        pool. ``drained=True`` books it as a drain departure (the engine
        flushed its partials first) rather than an eviction. Idempotent —
        deregistering an already-forgotten endpoint is a no-op."""
        out = self._call("POST", "/deregister_rollout_instance",
                         {"endpoint": endpoint, "drained": drained},
                         idempotent=True)
        if self.supervisor is not None:
            self.supervisor.forget_instance(endpoint)
        return out

    def generate(self, rid: str, input_ids: list[int], sampling_params: dict) -> GenerateResult:
        out = self._call("POST", "/generate", {
            "rid": rid, "input_ids": input_ids, "sampling_params": sampling_params})
        return self._to_result(out)

    def update_weight_version(self) -> int:
        v = int(self._call("POST", "/update_weight_version")["weight_version"])
        if self.supervisor is not None:
            self.supervisor.record_weight_version(v)
        return v

    def get_receive_instances(self, sender: str = "") -> dict:
        # NOT idempotent: the manager CAS-marks returned instances as
        # updating — a retry after a lost response would strand the first
        # claim until abort_weight_update
        return self._call("POST", "/get_receive_instances", {"sender": sender})

    def update_weights(self, instances: list[str], weight_version: int | None = None) -> dict:
        payload: dict[str, Any] = {"instances": instances}
        if weight_version is not None:
            payload["weight_version"] = weight_version
        return self._call("POST", "/update_weights", payload)

    def abort_weight_update(self, instances: list[str]) -> dict:
        return self._call("POST", "/abort_weight_update", {"instances": instances})

    def update_weight_senders(self, senders: list[str], groups_per_sender: int = 1) -> dict:
        out = self._call("PUT", "/update_weight_senders",
                         {"senders": senders,
                          "groups_per_sender": groups_per_sender},
                         idempotent=True)
        if self.supervisor is not None:
            self.supervisor.record_weight_senders(senders, groups_per_sender)
        return out

    def update_metrics(self, **stats) -> dict:
        return self._call("POST", "/update_metrics", stats, idempotent=True)

    def metrics_text(self, timeout: float = 5.0) -> str:
        """Raw Prometheus text from GET /metrics (the trainer scrapes this
        once per step and merges it into the step record as manager/*).
        No internal retry: a scrape miss degrades gracefully at the caller
        (RemoteRollout skips the merge and counts obs/scrape_failed) —
        retrying telemetry inside a step would trade step latency for a
        metric merge nobody is blocked on."""
        with obs.span("manager/metrics"):
            req = urllib.request.Request(self.endpoint + "/metrics",
                                         method="GET")
            t0 = time.monotonic()
            with urllib.request.urlopen(req, timeout=timeout) as r:
                text = r.read().decode()
            obs.observe("manager/scrape_s", time.monotonic() - t0)
            return text

    def shutdown_instances(self, skip_if_updating_weights: bool = False) -> dict:
        return self._call("POST", "/shutdown_instances",
                          {"skip_if_updating_weights": skip_if_updating_weights})

    def abort_local_requests(self) -> dict:
        return self._call("POST", "/abort_local_requests")

    def resume_local_instances(self) -> dict:
        return self._call("POST", "/resume_local_instances", idempotent=True)

    def reconcile(self, remote_endpoints: list[str], local_endpoints: list[str],
                  senders: list[str], groups_per_sender: int,
                  weight_version: int,
                  instance_versions: dict[str, int] | None = None) -> dict:
        """Idempotent bulk re-registration (supervisor replay after a
        manager respawn): already-known endpoints are kept as-is and the
        weight version is only ever raised, never reset.
        ``instance_versions`` replays pool membership's per-engine
        last-known weight versions so a respawned manager re-admits a
        healthy, caught-up fleet instead of orphaning it behind a
        redundant weight bootstrap."""
        return self._call("POST", "/reconcile", {
            "remote_endpoints": remote_endpoints,
            "local_endpoints": local_endpoints,
            "senders": senders,
            "groups_per_sender": groups_per_sender,
            "weight_version": weight_version,
            "instance_versions": dict(instance_versions or {}),
        }, idempotent=True)

    # -- streaming batch (the C7 StreamingBatchIterator role) -------------

    def batch_generate_stream(self, requests: list[dict],
                              max_local_gen_s: float | None = None
                              ) -> Iterator[GenerateResult]:
        """POST /batch_generate_requests; yields results as NDJSON lines
        arrive. The first 'notifier' line is consumed internally (it signals
        batch acceptance — reference stream_batch_iter.py:41-43). Transport
        failures (manager died mid-stream, truncated chunk) raise a typed
        :class:`ManagerTransportError` so RemoteRollout's stream-resume
        layer can re-issue only the unfinished rids."""
        payload: dict[str, Any] = {"requests": requests}
        if max_local_gen_s is not None:
            payload["max_local_gen_s"] = max_local_gen_s
        headers = {"Content-Type": "application/json"}
        headers.update(obs.trace_headers())
        req = urllib.request.Request(
            self.endpoint + "/batch_generate_requests",
            data=json.dumps(payload).encode(), method="POST",
            headers=headers)
        try:
            with obs.span("manager/batch_generate_requests",
                          n=len(requests)), \
                    urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                for raw in r:
                    line = raw.decode().strip()
                    if not line:
                        continue
                    try:
                        obj = json.loads(line)
                    except json.JSONDecodeError as exc:
                        # a line cut mid-byte by a dying manager is a
                        # transport fault, not a protocol error
                        raise ManagerTransportError(
                            f"truncated stream line: {exc}") from exc
                    if obj.get("type") == "notifier":
                        continue
                    if obj.get("type") == "progress":
                        # token-level progress: feed for the caller's
                        # salvage ledger (rollout/remote.py). Not terminal.
                        yield GenerateProgress(
                            rid=obj.get("rid", ""),
                            token_ids=[int(t) for t in
                                       obj.get("token_ids", [])],
                            logprobs=[float(x) for x in
                                      obj.get("logprobs", [])],
                            weight_version=int(obj.get("weight_version",
                                                       -1)))
                        continue
                    yield self._to_result(obj)
        except urllib.error.HTTPError:
            raise
        except _TRANSPORT_ERRORS as exc:
            raise ManagerTransportError(
                f"batch stream failed: {exc}") from exc

    @staticmethod
    def _to_result(out: dict) -> GenerateResult:
        return GenerateResult(
            rid=out.get("rid", ""),
            success=bool(out.get("success", False)),
            output_token_ids=[int(t) for t in out.get("output_token_ids", [])],
            output_token_logprobs=[float(x) for x in out.get("output_token_logprobs", [])],
            finish_reason=out.get("finish_reason", ""),
            error=out.get("error", ""),
            output_token_weight_versions=[
                int(v) for v in out.get("output_token_weight_versions", [])],
        )
