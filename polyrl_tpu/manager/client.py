"""ManagerClient + launcher — Python side of the rollout control plane.

Plays the roles of the reference's trainer-side HTTP calls
(``stream_batch_iter.py`` streaming batch iterator, C7;
``launcher.py:32-49`` spawn_rollout_manager; registration/metrics calls in
``stream_ray_trainer.py:691-704`` and ``sglang_http_async_engine.py:102-113``)
against the C++ ``polyrl-manager`` binary.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import time
import urllib.request
from dataclasses import dataclass
from typing import Any, Iterator

_CPP_DIR = os.path.join(os.path.dirname(__file__), "cpp")
_BINARY = os.path.join(_CPP_DIR, "polyrl-manager")


def build_manager(force: bool = False) -> str:
    """Build the C++ manager if needed; returns the binary path."""
    if force or not os.path.exists(_BINARY):
        subprocess.run(["make", "-C", _CPP_DIR], check=True, capture_output=True)
    return _BINARY


def spawn_rollout_manager(bind_addr: str = "0.0.0.0:0",
                          config_file: str | None = None,
                          extra_args: list[str] | None = None):
    """Start the manager subprocess; returns (Popen, port). Reads the
    'LISTENING <port>' line the binary prints (supports ephemeral ports)."""
    binary = build_manager()
    cmd = [binary, "--bind-addr", bind_addr]
    if config_file:
        cmd += ["--config-file", config_file]
    cmd += extra_args or []
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                            text=True)
    line = proc.stdout.readline().strip()
    if not line.startswith("LISTENING"):
        proc.kill()
        raise RuntimeError(f"manager failed to start: {line!r}")
    port = int(line.split()[1])
    return proc, port


@dataclass
class GenerateResult:
    rid: str
    success: bool
    output_token_ids: list[int]
    output_token_logprobs: list[float]
    finish_reason: str
    error: str = ""


class ManagerClient:
    def __init__(self, endpoint: str, timeout_s: float = 600.0):
        self.endpoint = endpoint if endpoint.startswith("http") else f"http://{endpoint}"
        self.timeout_s = timeout_s

    # -- plain JSON calls --------------------------------------------------

    def _call(self, method: str, path: str, payload: dict | None = None,
              timeout: float | None = None) -> dict:
        data = json.dumps(payload or {}).encode()
        req = urllib.request.Request(
            self.endpoint + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout or self.timeout_s) as r:
            return json.loads(r.read() or b"{}")

    def health(self) -> bool:
        try:
            return self._call("GET", "/health", timeout=3.0).get("status") == "ok"
        except Exception:
            return False

    def wait_healthy(self, deadline_s: float = 30.0) -> None:
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            if self.health():
                return
            time.sleep(0.1)
        raise TimeoutError("manager not healthy")

    def get_instances_status(self) -> dict:
        return self._call("GET", "/get_instances_status")

    def register_rollout_instance(self, instance_endpoint: str) -> dict:
        return self._call("POST", "/register_rollout_instance",
                          {"endpoint": instance_endpoint})

    def register_local_rollout_instances(self, endpoints: list[str]) -> dict:
        return self._call("POST", "/register_local_rollout_instances",
                          {"endpoints": endpoints})

    def generate(self, rid: str, input_ids: list[int], sampling_params: dict) -> GenerateResult:
        out = self._call("POST", "/generate", {
            "rid": rid, "input_ids": input_ids, "sampling_params": sampling_params})
        return self._to_result(out)

    def update_weight_version(self) -> int:
        return int(self._call("POST", "/update_weight_version")["weight_version"])

    def get_receive_instances(self, sender: str = "") -> dict:
        return self._call("POST", "/get_receive_instances", {"sender": sender})

    def update_weights(self, instances: list[str], weight_version: int | None = None) -> dict:
        payload: dict[str, Any] = {"instances": instances}
        if weight_version is not None:
            payload["weight_version"] = weight_version
        return self._call("POST", "/update_weights", payload)

    def abort_weight_update(self, instances: list[str]) -> dict:
        return self._call("POST", "/abort_weight_update", {"instances": instances})

    def update_weight_senders(self, senders: list[str], groups_per_sender: int = 1) -> dict:
        return self._call("PUT", "/update_weight_senders",
                          {"senders": senders, "groups_per_sender": groups_per_sender})

    def update_metrics(self, **stats) -> dict:
        return self._call("POST", "/update_metrics", stats)

    def shutdown_instances(self, skip_if_updating_weights: bool = False) -> dict:
        return self._call("POST", "/shutdown_instances",
                          {"skip_if_updating_weights": skip_if_updating_weights})

    def abort_local_requests(self) -> dict:
        return self._call("POST", "/abort_local_requests")

    def resume_local_instances(self) -> dict:
        return self._call("POST", "/resume_local_instances")

    # -- streaming batch (the C7 StreamingBatchIterator role) -------------

    def batch_generate_stream(self, requests: list[dict],
                              max_local_gen_s: float | None = None
                              ) -> Iterator[GenerateResult]:
        """POST /batch_generate_requests; yields results as NDJSON lines
        arrive. The first 'notifier' line is consumed internally (it signals
        batch acceptance — reference stream_batch_iter.py:41-43)."""
        payload: dict[str, Any] = {"requests": requests}
        if max_local_gen_s is not None:
            payload["max_local_gen_s"] = max_local_gen_s
        req = urllib.request.Request(
            self.endpoint + "/batch_generate_requests",
            data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            for raw in r:
                line = raw.decode().strip()
                if not line:
                    continue
                obj = json.loads(line)
                if obj.get("type") == "notifier":
                    continue
                yield self._to_result(obj)

    @staticmethod
    def _to_result(out: dict) -> GenerateResult:
        return GenerateResult(
            rid=out.get("rid", ""),
            success=bool(out.get("success", False)),
            output_token_ids=[int(t) for t in out.get("output_token_ids", [])],
            output_token_logprobs=[float(x) for x in out.get("output_token_logprobs", [])],
            finish_reason=out.get("finish_reason", ""),
            error=out.get("error", ""),
        )
