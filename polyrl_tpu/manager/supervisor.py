"""ManagerSupervisor — keeps the rollout manager alive.

The manager binary is the control-plane single point of failure the rest of
the fault-tolerance stack (engine eviction + token continuation below it,
stream resume above it) cannot absorb: before this layer,
``spawn_rollout_manager`` returned an unsupervised Popen and a manager
crash ended the run. The supervisor owns the subprocess, watches liveness
(process exit + ``/health`` probes), respawns with capped exponential
backoff, and replays *desired state* onto the fresh process through the
idempotent ``POST /reconcile`` route — registered remote/local instance
endpoints, weight-sender endpoints, and a weight-version floor — so a
manager crash costs one respawn latency, not the training run.

Desired state is fed from two directions:
- the trainer-side :class:`~polyrl_tpu.manager.client.ManagerClient`
  records its own registrations/sender updates/version bumps (``record_*``
  calls), and
- the health monitor snapshots ``/get_instances_status`` each probe, so
  instances that registered THEMSELVES from other processes
  (``python -m polyrl_tpu.rollout.serve`` workers) are replayed too.

The union is replayed; a stale endpoint self-heals on the new manager (its
health-check deadline deregisters it), which is cheap, while a lost
endpoint would silently shrink the pool, which is not.

Controller-resilience parity with async RL frameworks (LlamaRL
arxiv 2505.24034, MindSpeed RL arxiv 2507.19017) — see ARCHITECTURE.md
"Fault-tolerance layers".
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import time

from polyrl_tpu.manager.client import ManagerClient, spawn_rollout_manager

log = logging.getLogger(__name__)


class ManagerSupervisor:
    def __init__(self, bind_addr: str = "127.0.0.1:0",
                 config_file: str | None = None,
                 extra_args: list[str] | None = None,
                 respawn_backoff_s: float = 0.5,
                 respawn_backoff_max_s: float = 10.0,
                 health_interval_s: float = 1.0,
                 health_failures: int = 3,
                 spawn_deadline_s: float = 30.0,
                 log_path: str | None = None):
        self.bind_addr = bind_addr
        self.config_file = config_file
        self.extra_args = list(extra_args or [])
        self.respawn_backoff_s = respawn_backoff_s
        self.respawn_backoff_max_s = respawn_backoff_max_s
        self.health_interval_s = health_interval_s
        self.health_failures = max(1, health_failures)
        self.spawn_deadline_s = spawn_deadline_s
        # one stable log file across respawns (appended): the last words of
        # a crashed manager are exactly what a post-mortem needs
        self.log_path = log_path or os.path.join(
            tempfile.gettempdir(),
            f"polyrl-manager-supervised-{os.getpid()}.log")
        host = bind_addr.rsplit(":", 1)[0]
        self._host = "127.0.0.1" if host in ("0.0.0.0", "") else host
        self.proc = None
        self.port: int | None = None
        self.restarts = 0  # surfaced as fault/manager_restarts
        self._lock = threading.Lock()
        self._desired: dict = {"remote": set(), "local": set(),
                               "senders": [], "groups_per_sender": 1,
                               "weight_version": 0,
                               # pool membership: endpoint -> last-known
                               # weight version (replayed so a respawn does
                               # not orphan a caught-up fleet behind a
                               # redundant weight bootstrap)
                               "instance_versions": {}}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- desired state (replayed through /reconcile on every respawn) ------

    def record_remote_instances(self, endpoints: list[str]) -> None:
        with self._lock:
            self._desired["remote"].update(e for e in endpoints if e)

    def record_local_instances(self, endpoints: list[str]) -> None:
        with self._lock:
            self._desired["local"].update(e for e in endpoints if e)

    def record_weight_senders(self, senders: list[str],
                              groups_per_sender: int = 1) -> None:
        with self._lock:
            self._desired["senders"] = list(senders)
            self._desired["groups_per_sender"] = int(groups_per_sender)

    def record_weight_version(self, version: int) -> None:
        with self._lock:
            if version > self._desired["weight_version"]:
                self._desired["weight_version"] = int(version)

    def record_instance_version(self, endpoint: str, version: int) -> None:
        """Per-engine weight version (monotonic per endpoint)."""
        if not endpoint or version <= 0:
            return
        with self._lock:
            cur = self._desired["instance_versions"].get(endpoint, 0)
            if version > cur:
                self._desired["instance_versions"][endpoint] = int(version)

    def forget_instance(self, endpoint: str) -> None:
        """Drop a departed engine from desired state (graceful leave /
        preemption drill): replaying it onto a fresh manager would re-add
        a dead endpoint the pool just said goodbye to."""
        with self._lock:
            self._desired["remote"].discard(endpoint)
            self._desired["local"].discard(endpoint)
            self._desired["instance_versions"].pop(endpoint, None)

    # -- lifecycle ---------------------------------------------------------

    @property
    def endpoint(self) -> str:
        """host:port of the CURRENT manager process ("" before start)."""
        port = self.port
        return f"{self._host}:{port}" if port else ""

    def client(self, **kwargs) -> ManagerClient:
        """A ManagerClient bound to this supervisor (endpoint re-resolves
        across respawns; registrations recorded for replay)."""
        return ManagerClient(supervisor=self, **kwargs)

    def start(self) -> "ManagerSupervisor":
        """Spawn the first manager (raising loudly on startup failure — a
        misconfiguration must not be retried forever) and start the
        monitor thread."""
        self._spawn()
        self._thread = threading.Thread(target=self._monitor, daemon=True,
                                        name="manager-supervisor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        proc = self.proc
        if proc is not None and proc.poll() is None:
            proc.kill()

    # -- internals ---------------------------------------------------------

    def _spawn(self) -> None:
        proc, port = spawn_rollout_manager(
            self.bind_addr, config_file=self.config_file,
            extra_args=self.extra_args, log_path=self.log_path)
        try:
            self.proc = proc
            self.port = port
            probe = ManagerClient(self.endpoint)
            probe.wait_healthy(self.spawn_deadline_s)
            self._replay(probe)
        except Exception:
            proc.kill()  # never leak a half-started manager into a retry
            raise

    def _replay(self, client: ManagerClient) -> None:
        with self._lock:
            remote = sorted(self._desired["remote"])
            local = sorted(self._desired["local"])
            senders = list(self._desired["senders"])
            groups = self._desired["groups_per_sender"]
            version = self._desired["weight_version"]
            inst_versions = dict(self._desired["instance_versions"])
        if not (remote or local or senders or version):
            return  # nothing registered yet (first spawn)
        out = client.reconcile(remote, local, senders, groups, version,
                               instance_versions=inst_versions)
        log.info("manager reconciled: %s", out)

    def _snapshot(self, client: ManagerClient) -> None:
        """Fold the live registry into desired state so self-registered
        instances (serve.py workers) survive a respawn too."""
        try:
            st = client._call_once("GET", "/get_instances_status", timeout=3.0)
        except Exception:  # noqa: BLE001 — probe already decided liveness
            return
        with self._lock:
            for inst in st.get("instances", []):
                ep = inst.get("endpoint", "")
                if not ep:
                    continue
                key = "local" if inst.get("is_local") else "remote"
                self._desired[key].add(ep)
                # pool membership: the engine's last-known weight version
                # rides along so the replay can re-admit a caught-up fleet
                iv = int(inst.get("weight_version", -1))
                if iv > self._desired["instance_versions"].get(ep, 0):
                    self._desired["instance_versions"][ep] = iv
            v = int(st.get("weight_version", 0))
            if v > self._desired["weight_version"]:
                self._desired["weight_version"] = v

    def _monitor(self) -> None:
        probe = ManagerClient(supervisor=self)
        fails = 0
        backoff = self.respawn_backoff_s
        while not self._stop.wait(self.health_interval_s):
            proc = self.proc
            dead = proc is None or proc.poll() is not None
            if not dead and probe.health():
                fails = 0
                backoff = self.respawn_backoff_s
                self._snapshot(probe)
                continue
            fails += 1
            if not dead and fails < self.health_failures:
                continue  # transient: give a live process a grace window
            log.warning("manager %s (%d health failures); respawning",
                        "exited" if dead else "unresponsive", fails)
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10.0)
            fails = 0
            while not self._stop.is_set():
                try:
                    self._spawn()
                    self.restarts += 1
                    log.info("manager respawned on %s (restart #%d)",
                             self.endpoint, self.restarts)
                    break
                except Exception:  # noqa: BLE001 — keep trying with backoff
                    log.exception("manager respawn failed; retrying in %.1fs",
                                  backoff)
                    self._stop.wait(backoff)
                    backoff = min(backoff * 2, self.respawn_backoff_max_s)
