"""polyrl_tpu — TPU-native RL post-training framework.

A from-scratch, TPU-first implementation of the capability set of
Terra-Flux/PolyRL (disaggregated streaming PPO/GRPO for LLMs): JAX/pjit
GSPMD training over a (dp, fsdp, tp, sp, ep) mesh, a JAX inference engine for
rollout with per-token logprobs, an elastic rollout control plane with
token-level fault-tolerant continuation, and a versioned trainer→rollout
weight-transfer fabric. See SURVEY.md for the structural map of the
reference this build mirrors.
"""

__version__ = "0.1.0"
