"""Step-wise decode: jitted prefill + one-token decode step.

The fused ``RolloutEngine.generate`` while-loop is the throughput path; this
stepper is the SERVING path — the host drives one jitted step per token so
the HTTP server can stream ``output_token_logprobs`` as they are produced,
honor mid-decode aborts, and let the manager's token-level continuation see
partial outputs (reference: SGLang's streaming /generate consumed at
handlers.rs:215-251; abort_request at sglang_http_async_engine.py:286-298).

Shape discipline: one compiled (prefill, step) pair per
(batch_bucket, prompt_bucket, new_bucket, sampling-group); the KV cache is
sized pb + nb and written at a traced index, so every step reuses the same
executable.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from polyrl_tpu.models import decoder
from polyrl_tpu.rollout.engine import next_bucket, pack_left_padded
from polyrl_tpu.rollout.sampling import SamplingParams, sample_token


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StepState:
    """Device-resident decode state between steps (a pytree, so it flows
    through jit boundaries and donation)."""

    step: jax.Array          # scalar int32
    done: jax.Array          # [bb] bool
    last_logits: jax.Array   # [bb, V]
    cache: Any
    cache_mask: jax.Array    # [bb, pb+nb]
    prompt_len: jax.Array    # [bb] int32
    rng: jax.Array


class StepDecoder:
    def __init__(self, engine, new_buckets: tuple[int, ...] = (64, 128, 256, 512,
                                                              1024, 2048, 4096)):
        self.engine = engine
        self.cfg = engine.cfg
        self.new_buckets = new_buckets
        self._prefill: dict = {}
        self._step: dict = {}

    # -- compiled pieces ----------------------------------------------------

    def _build_prefill(self, bb: int, pb: int, nb: int):
        cfg = self.cfg
        kv_dtype = self.engine.kv_cache_dtype
        max_total = pb + nb

        def prefill(params, ids, mask, rng):
            positions = jnp.maximum(jnp.cumsum(mask, axis=-1) - 1, 0).astype(jnp.int32)
            cache = decoder.make_cache(cfg, bb, max_total, dtype=kv_dtype)
            cache_mask = jnp.concatenate(
                [mask, jnp.zeros((bb, nb), mask.dtype)], axis=-1)
            logits, cache = decoder.forward(
                params, cfg, ids, positions, cache_mask, cache=cache, write_idx=0)
            prompt_len = jnp.sum(mask, axis=-1).astype(jnp.int32)
            done = prompt_len == 0  # batch-padding rows start done
            return StepState(jnp.int32(0), done, logits[:, -1, :], cache,
                             cache_mask, prompt_len, rng)

        return jax.jit(prefill)

    def _build_step(self, bb: int, pb: int, nb: int, sp: SamplingParams):
        pad = self.engine.pad_token_id
        cfg = self.cfg
        stop_ids = jnp.asarray(sp.stop_token_ids or (-1,), dtype=jnp.int32)

        def step(params, st: StepState, abort_mask, row_limit):
            rng, sub = jax.random.split(st.rng)
            done = st.done | abort_mask
            token, logp = sample_token(st.last_logits, sub, sp)
            token = jnp.where(done, pad, token)
            logp = jnp.where(done, 0.0, logp)
            hit_stop = jnp.any(token[:, None] == stop_ids[None, :], axis=-1)
            new_done = done | hit_stop | (st.step + 1 >= row_limit)

            write_idx = pb + st.step
            cache_mask = jax.lax.dynamic_update_slice(
                st.cache_mask,
                jnp.where(done, 0.0, 1.0).astype(st.cache_mask.dtype)[:, None],
                (0, write_idx))
            pos = (st.prompt_len + st.step)[:, None]
            step_logits, cache = decoder.forward(
                params, cfg, token[:, None], pos, cache_mask,
                cache=st.cache, write_idx=write_idx)
            new_state = StepState(st.step + 1, new_done, step_logits[:, 0, :],
                                  cache, cache_mask, st.prompt_len, rng)
            return new_state, token, logp, new_done

        return jax.jit(step, donate_argnums=(1,))

    # -- host-driven streaming generate ------------------------------------

    def generate_stream(self, prompt_ids: list[list[int]],
                        sampling: SamplingParams,
                        max_new: list[int] | None = None,
                        rng: jax.Array | None = None,
                        abort_flags: list | None = None):
        """Yields per-step dicts {row, token, logprob, done, finish_reason}.

        ``max_new`` allows per-row budgets (continuation shrinks
        max_new_tokens — utils.rs:256-291); ``abort_flags`` is a list of
        ``threading.Event``-likes checked between steps.
        """
        n = len(prompt_ids)
        bb = next_bucket(n, self.engine.batch_buckets)
        pb = next_bucket(max(len(p) for p in prompt_ids), self.engine.prompt_buckets)
        limits = max_new if max_new is not None else [sampling.max_new_tokens] * n
        nb = next_bucket(max(limits), self.new_buckets)

        ids, mask = pack_left_padded(prompt_ids, self.engine.pad_token_id, bb, pb)
        row_limit = np.zeros((bb,), np.int32)
        row_limit[:n] = np.asarray(limits, np.int32)

        pkey = (bb, pb, nb)
        if pkey not in self._prefill:
            self._prefill[pkey] = self._build_prefill(bb, pb, nb)
        skey = (bb, pb, nb, sampling.group_key())
        if skey not in self._step:
            self._step[skey] = self._build_step(bb, pb, nb, sampling)

        rng = rng if rng is not None else jax.random.PRNGKey(
            np.random.randint(0, 2**31 - 1))
        state = self._prefill[pkey](self.engine.params, ids, mask, rng)
        row_limit_dev = jnp.asarray(row_limit)

        prev_done = np.zeros((bb,), bool)
        prev_done[n:] = True
        stop_set = set(sampling.stop_token_ids)
        max_steps = int(max(limits))
        for _ in range(max_steps):
            abort = np.zeros((bb,), bool)
            if abort_flags is not None:
                for i in range(n):
                    if abort_flags[i] is not None and abort_flags[i].is_set():
                        abort[i] = True
            state, token, logp, done = self._step[skey](
                self.engine.params, state, jnp.asarray(abort), row_limit_dev)
            token_h, logp_h, done_h = (np.asarray(token), np.asarray(logp),
                                       np.asarray(done))
            for i in range(n):
                if prev_done[i]:
                    continue
                if abort[i]:
                    yield {"row": i, "token": None, "logprob": None,
                           "done": True, "finish_reason": "abort"}
                    continue
                t = int(token_h[i])
                fin = bool(done_h[i])
                reason = ""
                if fin:
                    reason = "stop" if t in stop_set else "length"
                yield {"row": i, "token": t, "logprob": float(logp_h[i]),
                       "done": fin, "finish_reason": reason}
            prev_done = done_h | abort
            if prev_done.all():
                break
