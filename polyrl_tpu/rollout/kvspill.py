"""Host-RAM KV spill tier — the backing store behind the paged HBM pool
(ARCHITECTURE.md "KV spill tier").

One chip's HBM bounds concurrent sessions; the page ledger
(rollout/kvledger.py) already knows which resident pages are COLD and who
owns them. This module adds the tier the ledger was built to enable: cold
published prefix-cache pages are copied device→host, their physical pages
return to the :class:`~polyrl_tpu.rollout.cb_engine.PageAllocator`, and the
KV content survives in host RAM until a prefix-cache hit (or a resuming
session) restores it into a freshly allocated page — at a NEW physical
index, which is safe because every consumer goes through the page-table
indirection (the PR 4 salvage-republish machinery relies on the same
property).

Design (mirrors the engine's fetcher-thread pattern):

- :meth:`HostSpillPool.spill` takes the extracted per-page device slices
  (``[L, Hkv, n, page_size, D]`` stacked over layers) and queues them on a
  DOUBLE-BUFFERED background lane: a dedicated copy thread owns the
  blocking ``device_get``; at most ``lane_depth`` batches are in flight, so
  spilling never stalls the engine loop and the transient HBM held by the
  extracted slices stays bounded. Until a batch lands, its entries keep
  their device buffers — a restore that races the copy just reads those
  (synchronous fallback, same discipline as the dead-fetcher drain path).
- The engine frees the physical pages IMMEDIATELY after extraction: the
  slices are independent device buffers ordered after every previously
  dispatched write (pool data dependency), and nothing can write the freed
  pages until a later prefill reallocates them — which the same dependency
  orders after the extraction.
- :meth:`fetch` returns the page's host KV (blocking out an in-flight copy
  if needed); :meth:`drop` discards entries (restore consumed it, or an
  abort/flush while spilled frees the host tier).

Byte accounting (``resident_bytes`` vs ``capacity_bytes``) backs the
``--kv-spill-host-gb`` knob; the LEDGER owns the page-count/byte counters
that feed ``kv_spilled_frac`` and reconciliation (HBM-resident + spilled ==
accounted) — this pool only reports host-side truth.

Thread-safety: ``spill``/``fetch``/``drop`` run on the engine loop thread
(under ``_pool_lock``); the copy thread only moves queued batches from
device refs to host arrays under the pool's own condition variable.
"""

from __future__ import annotations

import dataclasses
import logging
import threading

import numpy as np

log = logging.getLogger(__name__)


@dataclasses.dataclass
class _SpillEntry:
    handle: int
    nbytes: int
    # exactly one of (host k/v) or the batch device ref is set; the batch
    # ref is dropped when the background copy lands (that is what releases
    # the transient HBM the extracted slices pin)
    k_host: np.ndarray | None = None
    v_host: np.ndarray | None = None
    # (k_batch, v_batch, page index into the batch) while in flight
    dev: tuple | None = None
    dead: bool = False  # dropped while the copy was still in flight


class HostSpillPool:
    """Pinned host-memory backing tier for spilled KV pages."""

    def __init__(self, capacity_bytes: int, lane_depth: int = 2):
        self.capacity_bytes = int(capacity_bytes)
        self.lane_depth = max(1, int(lane_depth))
        self._cv = threading.Condition()
        self._entries: dict[int, _SpillEntry] = {}
        self._next_handle = 0
        # background copy lane: (handles, k_dev, v_dev) batches awaiting
        # device_get; bounded by lane_depth (double-buffered by default)
        self._lane: list[tuple[list[int], object, object]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # host-side truth (cumulative; the ledger owns the page counters)
        self.resident_bytes = 0
        self.bytes_spilled = 0
        self.bytes_restored = 0
        self.copy_batches = 0
        self.sync_fetches = 0  # restores that beat the background copy

    # -- lifecycle -----------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            if self._stop.is_set():
                return
            self._thread = threading.Thread(target=self._copy_loop,
                                            name="kv-spill-copy",
                                            daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    # -- spill side (engine loop thread) -------------------------------------

    def lane_free(self) -> bool:
        """Backpressure: the double-buffered lane has room for one more
        batch (a full lane means the copy thread is behind — the sweep
        skips this dispatch instead of queueing unbounded device refs)."""
        with self._cv:
            return len(self._lane) < self.lane_depth

    def can_spill(self, n_pages: int, page_bytes: int) -> bool:
        with self._cv:
            return (len(self._lane) < self.lane_depth
                    and self.resident_bytes + n_pages * page_bytes
                    <= self.capacity_bytes)

    def spill(self, k_dev, v_dev, n_pages: int,
              page_bytes: int) -> list[int]:
        """Queue ``n_pages`` extracted page slices (``k_dev``/``v_dev`` are
        ``[L, Hkv, n_pages, page_size, D]`` device arrays) for the
        background device→host copy. Returns one handle per page (index
        ``i`` of the slice ↔ handle ``i``)."""
        handles: list[int] = []
        with self._cv:
            for i in range(n_pages):
                h = self._next_handle
                self._next_handle += 1
                # the entry keeps a ref to the WHOLE batch + its index: the
                # copy thread lands the batch in ONE device_get; a restore
                # that beats it slices its own page out synchronously
                self._entries[h] = _SpillEntry(
                    handle=h, nbytes=page_bytes, dev=(k_dev, v_dev, i))
                handles.append(h)
            self._lane.append((list(handles), k_dev, v_dev))
            self.resident_bytes += n_pages * page_bytes
            self.bytes_spilled += n_pages * page_bytes
            self._cv.notify_all()
        self._ensure_thread()
        return handles

    # -- copy thread ----------------------------------------------------------

    def _copy_loop(self) -> None:
        import jax

        while not self._stop.is_set():
            with self._cv:
                if not self._lane:
                    self._cv.wait(timeout=0.05)
                    continue
                handles, k_dev, v_dev = self._lane[0]
            try:
                k_host, v_host = jax.device_get([k_dev, v_dev])
            except Exception:  # noqa: BLE001 — a poisoned buffer must not
                # kill the lane; the entries keep their device refs and a
                # later fetch retries (or surfaces) synchronously
                log.exception("kv spill copy failed; entries stay on device")
                with self._cv:
                    if self._lane and self._lane[0][0] is handles:
                        self._lane.pop(0)
                    self._cv.notify_all()
                continue
            k_host = np.asarray(k_host)
            v_host = np.asarray(v_host)
            with self._cv:
                for i, h in enumerate(handles):
                    e = self._entries.get(h)
                    if e is None or e.dead or e.k_host is not None:
                        continue  # dropped or sync-fetched while in flight
                    e.k_host = np.ascontiguousarray(k_host[:, :, i])
                    e.v_host = np.ascontiguousarray(v_host[:, :, i])
                    e.dev = None
                if self._lane and self._lane[0][0] is handles:
                    self._lane.pop(0)
                self.copy_batches += 1
                self._cv.notify_all()

    # -- restore / drop side (engine loop thread) -----------------------------

    def fetch(self, handle: int) -> tuple[np.ndarray, np.ndarray]:
        """The page's host KV (``[L, Hkv, page_size, D]`` each). A fetch
        that beats the background copy lands the page's own slice
        synchronously (device refs are per-page views of the batch)."""
        with self._cv:
            e = self._entries[handle]
            if e.k_host is not None:
                return e.k_host, e.v_host
            k_dev, v_dev, i = e.dev
        import jax

        k_host, v_host = (np.asarray(a) for a in jax.device_get(
            [k_dev[:, :, i], v_dev[:, :, i]]))
        with self._cv:
            if e.k_host is None:
                e.k_host, e.v_host = k_host, v_host
                e.dev = None
                self.sync_fetches += 1
            return e.k_host, e.v_host

    def drop(self, handles, restored: bool = False) -> None:
        """Discard entries: a restore consumed them (``restored=True``,
        bytes move to the restored counter) or the content died while
        spilled (abort / cache flush / weight swap — both tiers freed)."""
        with self._cv:
            for h in handles:
                e = self._entries.pop(h, None)
                if e is None:
                    continue
                e.dead = True  # an in-flight copy discards it on landing
                self.resident_bytes -= e.nbytes
                if restored:
                    self.bytes_restored += e.nbytes
            self._cv.notify_all()

    # -- views ----------------------------------------------------------------

    @property
    def resident_pages(self) -> int:
        with self._cv:
            return len(self._entries)

    def stats(self) -> dict:
        """Host-side truth for the statusz ``memory.spill.host`` block."""
        with self._cv:
            return {
                "resident_pages": len(self._entries),
                "resident_bytes": int(self.resident_bytes),
                "capacity_bytes": int(self.capacity_bytes),
                "bytes_spilled": int(self.bytes_spilled),
                "bytes_restored": int(self.bytes_restored),
                "copy_batches": int(self.copy_batches),
                "sync_fetches": int(self.sync_fetches),
                "lane_inflight": len(self._lane),
                "lane_depth": self.lane_depth,
            }
