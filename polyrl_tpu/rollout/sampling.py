"""Token sampling: temperature / top-k / top-p, jit-safe, batched.

Equivalent role to SGLang's sampler in the reference rollout path (SURVEY.md
§2.2 row 1). All functions operate on [B, V] f32 logits and are shape-static
so they compile once per (batch, vocab) bucket.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

NEG_INF = jnp.finfo(jnp.float32).min


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Static sampling configuration (hashable → usable as jit static arg)."""

    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    max_new_tokens: int = 128
    stop_token_ids: tuple[int, ...] = ()

    def group_key(self) -> tuple:
        """Batching key: requests differing only in max_new_tokens can share
        one compiled step fn (per-row budgets are a traced arg)."""
        return (self.temperature, self.top_p, self.top_k, self.stop_token_ids)

    @staticmethod
    def from_dict(d: dict) -> "SamplingParams":
        return SamplingParams(
            temperature=float(d.get("temperature", 1.0)),
            top_p=float(d.get("top_p", 1.0)),
            top_k=int(d.get("top_k", 0)),
            # clamp: 0/negative budgets would yield an empty stream and hang
            # the serving handler waiting for tokens that never come
            max_new_tokens=max(int(d.get("max_new_tokens", 128)), 1),
            stop_token_ids=tuple(d.get("stop_token_ids", ())),
        )


def apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    if k <= 0:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens while cumulative prob (exclusive) < p; always keep top-1
    cutoff_mask = cum - probs < p
    kept = jnp.sum(cutoff_mask, axis=-1, keepdims=True)
    threshold = jnp.take_along_axis(sorted_logits, jnp.maximum(kept - 1, 0), axis=-1)
    return jnp.where(logits < threshold, NEG_INF, logits)


def _filtered_scaled(
    logits: jnp.ndarray,  # [S, V] f32
    temps: jnp.ndarray,   # [S] f32
    top_ps: jnp.ndarray,  # [S] f32
    top_ks: jnp.ndarray,  # [S] int32
    use_filters: bool,
) -> jnp.ndarray:
    """Temperature-scaled logits with per-row top-k/top-p masks applied —
    THE sampling distribution (shared by the plain sampler and the
    speculative verify sampler, which must accept/reject against exactly
    the distribution tokens are sampled from)."""
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    if use_filters:
        v = logits.shape[-1]
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        idx_k = jnp.clip(top_ks - 1, 0, v - 1)
        thr_k = jnp.take_along_axis(sorted_desc, idx_k[:, None], axis=-1)
        scaled = jnp.where((top_ks[:, None] > 0) & (scaled < thr_k), NEG_INF, scaled)
        sorted2 = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted2, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        kept = jnp.sum(cum - probs < top_ps[:, None], axis=-1, keepdims=True)
        thr_p = jnp.take_along_axis(sorted2, jnp.maximum(kept - 1, 0), axis=-1)
        scaled = jnp.where(scaled < thr_p, NEG_INF, scaled)
    return scaled


def sample_token_vec(
    logits: jnp.ndarray,  # [S, V] f32
    rng: jax.Array,
    temps: jnp.ndarray,   # [S] f32; 0 = greedy
    top_ps: jnp.ndarray,  # [S] f32; 1 = disabled
    top_ks: jnp.ndarray,  # [S] int32; 0 = disabled
    use_filters: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row sampling params as TRACED arrays — the continuous-batching
    engine mixes requests with different sampling configs in one compiled
    step (the reference gets this from SGLang's per-request sampler). Set
    ``use_filters=False`` (static) to skip the two [S, V] sorts when every
    live request runs plain temperature sampling."""
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    greedy_logp = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), greedy_tok[:, None], axis=-1)[:, 0]

    scaled = _filtered_scaled(logits, temps, top_ps, top_ks, use_filters)
    logp_all = jax.nn.log_softmax(scaled, axis=-1)
    tok = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    logp = jnp.take_along_axis(logp_all, tok[:, None], axis=-1)[:, 0]

    is_greedy = temps <= 0.0
    token = jnp.where(is_greedy, greedy_tok, tok)
    logp = jnp.where(is_greedy, greedy_logp, logp)
    return token, logp


def spec_verify_sample_vec(
    logits: jnp.ndarray,  # [S, m, V] f32 — verify logits: [s, i] is the
                          # next-token distribution AFTER draft token i-1
                          # (position 0 follows the slot's last real token)
    draft: jnp.ndarray,   # [S, m-1] int32 — deterministic (ngram) proposals
    rng: jax.Array,
    temps: jnp.ndarray,   # [S] f32; 0 = greedy
    top_ps: jnp.ndarray,
    top_ks: jnp.ndarray,
    use_filters: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Speculative (prompt-lookup) verify sampling. Returns
    ``(tokens [S, m], logps [S, m], n_acc [S])``: per slot the first
    ``n_acc`` tokens are accepted draft tokens and position ``n_acc`` holds
    the replacement/bonus sample — so ``n_acc + 1`` tokens are emitted.

    Distribution-exact for a deterministic proposal q = δ(draft):
    accept draft ``d`` with prob ``p(d)`` (= min(1, p/q)); on rejection
    sample from ``normalize(max(p - q, 0))`` = p with d masked out; after
    accepting ALL drafts, the bonus token samples from the last verify
    distribution unadjusted. Greedy rows accept iff argmax == d and replace
    with the argmax, which makes spec output token-EXACT vs plain greedy
    decode. ``p`` is the engine's real sampling distribution
    (temperature + top-k/top-p via ``_filtered_scaled``)."""
    s, m, v = logits.shape
    flat = logits.reshape(s * m, v)
    rep = lambda a: jnp.repeat(a, m, axis=0)  # noqa: E731
    scaled = _filtered_scaled(flat, rep(temps), rep(top_ps), rep(top_ks),
                              use_filters).reshape(s, m, v)
    logp_all = jax.nn.log_softmax(scaled, axis=-1)          # [S, m, V]
    raw_logp = jax.nn.log_softmax(logits, axis=-1)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, m]
    is_greedy = temps <= 0.0                                 # [S]

    r_accept, r_repl = jax.random.split(rng)
    p_draft = jnp.exp(jnp.take_along_axis(
        logp_all[:, : m - 1], draft[:, :, None], axis=-1))[:, :, 0]  # [S,m-1]
    u = jax.random.uniform(r_accept, (s, m - 1))
    acc = jnp.where(is_greedy[:, None], greedy_tok[:, : m - 1] == draft,
                    u < p_draft)                             # [S, m-1]
    prefix = jnp.cumprod(acc.astype(jnp.int32), axis=-1)     # [S, m-1]
    n_acc = prefix.sum(axis=-1).astype(jnp.int32)            # [S]

    # replacement distribution per position: draft token masked out
    # (positions < m-1); the bonus position m-1 is unadjusted. In greedy
    # rows rejection implies argmax != draft, so the argmax is unaffected
    # by the mask — replacement = argmax keeps token-exactness.
    adj = scaled.at[
        jnp.arange(s)[:, None], jnp.arange(m - 1)[None], draft].set(NEG_INF)
    repl = jax.random.categorical(
        r_repl, adj.reshape(s * m, v), axis=-1).reshape(s, m).astype(jnp.int32)
    repl = jnp.where(is_greedy[:, None], greedy_tok, repl)

    tokens = jnp.concatenate(
        [draft, jnp.zeros((s, 1), jnp.int32)], axis=1)       # [S, m]
    sel = n_acc[:, None]
    tokens = jnp.where(jnp.arange(m)[None] == sel,
                       jnp.take_along_axis(repl, sel, axis=1), tokens)
    # reported logp = target-model logp of the emitted token (the marginal
    # of speculative sampling IS the target distribution): filtered dist
    # for sampled rows, raw log-softmax for greedy rows — matching
    # sample_token_vec's convention exactly.
    lp_f = jnp.take_along_axis(logp_all, tokens[:, :, None], axis=-1)[:, :, 0]
    lp_g = jnp.take_along_axis(raw_logp, tokens[:, :, None], axis=-1)[:, :, 0]
    logps = jnp.where(is_greedy[:, None], lp_g, lp_f)
    return tokens, logps, n_acc


def sample_token(
    logits: jnp.ndarray,  # [B, V] f32
    rng: jax.Array,
    params: SamplingParams,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (token [B] int32, logprob [B] f32 of the sampled token under
    the post-temperature/filter distribution — the same semantics as the
    reference engine's ``output_token_logprobs`` used for token-level
    continuation, SURVEY.md §3.4)."""
    if params.temperature == 0.0:
        token = jnp.argmax(logits, axis=-1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return token.astype(jnp.int32), jnp.take_along_axis(logp, token[:, None], axis=-1)[:, 0]

    scaled = logits / params.temperature
    scaled = apply_top_k(scaled, params.top_k)
    scaled = apply_top_p(scaled, params.top_p)
    logp = jax.nn.log_softmax(scaled, axis=-1)
    token = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    token_logp = jnp.take_along_axis(logp, token[:, None], axis=-1)[:, 0]
    return token, token_logp
