"""Token sampling: temperature / top-k / top-p, jit-safe, batched.

Equivalent role to SGLang's sampler in the reference rollout path (SURVEY.md
§2.2 row 1). All functions operate on [B, V] f32 logits and are shape-static
so they compile once per (batch, vocab) bucket.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

NEG_INF = jnp.finfo(jnp.float32).min


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Static sampling configuration (hashable → usable as jit static arg)."""

    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    max_new_tokens: int = 128
    stop_token_ids: tuple[int, ...] = ()

    def group_key(self) -> tuple:
        """Batching key: requests differing only in max_new_tokens can share
        one compiled step fn (per-row budgets are a traced arg)."""
        return (self.temperature, self.top_p, self.top_k, self.stop_token_ids)

    @staticmethod
    def from_dict(d: dict) -> "SamplingParams":
        return SamplingParams(
            temperature=float(d.get("temperature", 1.0)),
            top_p=float(d.get("top_p", 1.0)),
            top_k=int(d.get("top_k", 0)),
            # clamp: 0/negative budgets would yield an empty stream and hang
            # the serving handler waiting for tokens that never come
            max_new_tokens=max(int(d.get("max_new_tokens", 128)), 1),
            stop_token_ids=tuple(d.get("stop_token_ids", ())),
        )


def apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    if k <= 0:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens while cumulative prob (exclusive) < p; always keep top-1
    cutoff_mask = cum - probs < p
    kept = jnp.sum(cutoff_mask, axis=-1, keepdims=True)
    threshold = jnp.take_along_axis(sorted_logits, jnp.maximum(kept - 1, 0), axis=-1)
    return jnp.where(logits < threshold, NEG_INF, logits)


def sample_token_vec(
    logits: jnp.ndarray,  # [S, V] f32
    rng: jax.Array,
    temps: jnp.ndarray,   # [S] f32; 0 = greedy
    top_ps: jnp.ndarray,  # [S] f32; 1 = disabled
    top_ks: jnp.ndarray,  # [S] int32; 0 = disabled
    use_filters: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row sampling params as TRACED arrays — the continuous-batching
    engine mixes requests with different sampling configs in one compiled
    step (the reference gets this from SGLang's per-request sampler). Set
    ``use_filters=False`` (static) to skip the two [S, V] sorts when every
    live request runs plain temperature sampling."""
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    greedy_logp = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), greedy_tok[:, None], axis=-1)[:, 0]

    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    if use_filters:
        v = logits.shape[-1]
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        idx_k = jnp.clip(top_ks - 1, 0, v - 1)
        thr_k = jnp.take_along_axis(sorted_desc, idx_k[:, None], axis=-1)
        scaled = jnp.where((top_ks[:, None] > 0) & (scaled < thr_k), NEG_INF, scaled)
        sorted2 = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted2, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        kept = jnp.sum(cum - probs < top_ps[:, None], axis=-1, keepdims=True)
        thr_p = jnp.take_along_axis(sorted2, jnp.maximum(kept - 1, 0), axis=-1)
        scaled = jnp.where(scaled < thr_p, NEG_INF, scaled)
    logp_all = jax.nn.log_softmax(scaled, axis=-1)
    tok = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    logp = jnp.take_along_axis(logp_all, tok[:, None], axis=-1)[:, 0]

    is_greedy = temps <= 0.0
    token = jnp.where(is_greedy, greedy_tok, tok)
    logp = jnp.where(is_greedy, greedy_logp, logp)
    return token, logp


def sample_token(
    logits: jnp.ndarray,  # [B, V] f32
    rng: jax.Array,
    params: SamplingParams,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (token [B] int32, logprob [B] f32 of the sampled token under
    the post-temperature/filter distribution — the same semantics as the
    reference engine's ``output_token_logprobs`` used for token-level
    continuation, SURVEY.md §3.4)."""
    if params.temperature == 0.0:
        token = jnp.argmax(logits, axis=-1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return token.astype(jnp.int32), jnp.take_along_axis(logp, token[:, None], axis=-1)[:, 0]

    scaled = logits / params.temperature
    scaled = apply_top_k(scaled, params.top_k)
    scaled = apply_top_p(scaled, params.top_p)
    logp = jax.nn.log_softmax(scaled, axis=-1)
    token = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    token_logp = jnp.take_along_axis(logp, token[:, None], axis=-1)[:, 0]
    return token, token_logp
