"""Fault-injection harness for the rollout plane (token-level continuous
generation's test surface: SURVEY.md §5.3 "no fault-injection harness
exists; the build should add one").

One :class:`FaultInjector` instance can be attached at two seams:

- **engine/server side** (``RolloutServer.fault``): observes every
  admission and every outgoing stream line. Configurable kill-after-N-tokens
  (trips the request's abort event — with ``salvage_partials`` the engine
  flushes a partial and the manager's continuation resumes it elsewhere),
  chunk corruption (emits one unparseable line — the manager's decode-error
  eviction path), stream stall, and a /drain trigger after N admissions
  (graceful-preemption rehearsal).
- **trainer/client side** (``RemoteRollout(fault_injector=...)``): wraps the
  manager batch stream and raises a ``ManagerTransportError`` once every
  still-pending rid has salvaged at least ``stream_kill_min_progress``
  tokens — killing the stream at the worst possible moment so the salvage
  ledger's suffix re-issue is exercised for EVERY request.

The weight-push fabric has its own sibling pair —
:class:`TransferFaultConfig` / :class:`TransferFaultInjector` (config
``transfer.fault_injection.*``) — injecting frame corruption on the wire,
stream stalls past the bandwidth-keyed push deadline, and control-channel
kills mid-round, so the verified/resumable push path (ARCHITECTURE.md
"Weight-fabric fault tolerance") is drillable end to end.

Faults are keyed by the request's *base* rid (the manager appends ``#a<n>``
per attempt), so ``once_per_request`` means once per logical request across
every retry/continuation/suffix-resume, which keeps fault runs terminating.

Driven from config (``rollout.fault_injection.*``), ``bench.py --chaos``,
and tests (tests/test_token_salvage.py, tests/test_salvage_chaos.py).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

log = logging.getLogger(__name__)


@dataclasses.dataclass
class FaultInjectionConfig:
    enabled: bool = False
    # -- engine/server-side triggers (RolloutServer.fault) -----------------
    kill_after_tokens: int = 0     # abort a request after N streamed tokens
    kill_limit: int = -1           # total kill budget (-1 = unlimited)
    once_per_request: bool = True  # at most one kill per logical rid
    corrupt_after_tokens: int = 0  # replace the Nth line with garbage
    corrupt_limit: int = 1         # total corrupted lines budget
    stall_s: float = 0.0           # stall each stream once, this long,
    stall_after_tokens: int = 1    #   after N tokens
    stall_after_requests: int = 0  # arm stalls only after N admissions
    #   (lets a run establish a healthy baseline first — the flight
    #   recorder's anomaly drill stalls step K, not step 1)
    stall_limit: int = -1          # total stall budget (-1 = unlimited)
    drain_after_requests: int = 0  # POST /drain semantics after N admissions
    # -- trainer/client-side trigger (RemoteRollout.fault_injector) --------
    stream_kill_times: int = 0       # how many manager streams to kill
    stream_kill_min_progress: int = 1  # fire only once EVERY pending rid
    #                                    has salvaged >= this many tokens
    # -- pool-drill trigger: kill a whole ENGINE mid-batch ------------------
    # Fires the registered ``engine_killer`` callback (tests/bench attach
    # e.g. ``server.kill`` — death WITHOUT notice) once the stream has
    # forwarded >= engine_kill_min_progress progress tokens, i.e. while
    # requests are provably mid-decode on the pool. Recovery is the pool's
    # job: heartbeat eviction + manager continuation on survivors.
    engine_kill_times: int = 0
    engine_kill_min_progress: int = 1


def base_rid(rid: str) -> str:
    """Strip the manager's per-attempt ``#a<n>`` suffix: fault bookkeeping
    must follow the logical request across retries and continuations."""
    return rid.rsplit("#a", 1)[0]


# --------------------------------------------------------------------------
# Transfer-plane faults (the weight-push fabric's chaos surface)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class TransferFaultConfig:
    """Transfer-plane faults (config ``transfer.fault_injection.*``).

    All triggers are budgeted and optionally targeted at one instance by
    endpoint substring (empty = any), and each can be gated behind N clean
    push attempts to the matching instance (``*_after_attempts``) so a
    run's bootstrap catch-up push lands clean before the chaos arms —
    attempts are counted by ``SenderAgent`` via :meth:`note_attempt`."""
    enabled: bool = False
    # flip one payload byte of this many wire frames (the CRC32 trailer is
    # computed over the TRUE bytes, so the receiver detects and rejects)
    corrupt_frames: int = 0
    corrupt_instance: str = ""
    corrupt_after_attempts: int = 0
    # stall a stream before its first frame — a stall longer than the
    # bandwidth-keyed push deadline fails the attempt by timeout
    stall_s: float = 0.0
    stall_streams: int = -1        # total stall budget (-1 = unlimited)
    stall_instance: str = ""
    stall_after_attempts: int = 0
    # close the sender->receiver control channel right before the verify
    # handshake (mid-round control-plane death: the receiver must
    # reconnect and the retry re-push the round)
    kill_control_rounds: int = 0
    kill_control_instance: str = ""
    kill_control_after_attempts: int = 0


class TransferFaultInjector:
    """Sibling of :class:`FaultInjector` for the weight-push fabric;
    counters are cumulative and public (tests and ``bench.py
    --push-chaos`` report them). Stalls sleep interruptibly —
    ``SenderAgent.stop()`` calls :meth:`stop` so a teardown mid-drill
    never waits out a sleeping fault."""

    def __init__(self, cfg: TransferFaultConfig | None = None, **overrides):
        if cfg is None:
            cfg = TransferFaultConfig(enabled=True, **overrides)
        elif overrides:
            cfg = dataclasses.replace(cfg, **overrides)
        self.cfg = cfg
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._attempts: dict[str, int] = {}  # instance -> push attempts
        # telemetry
        self.corruptions = 0
        self.stalls = 0
        self.control_kills = 0

    def stop(self) -> None:
        self._stop.set()

    def counters(self) -> dict[str, float]:
        return {
            "fault/transfer_corruptions": float(self.corruptions),
            "fault/transfer_stalls": float(self.stalls),
            "fault/transfer_control_kills": float(self.control_kills),
        }

    def note_attempt(self, instance: str) -> None:
        """Called by the sender at the start of every push attempt — the
        ``*_after_attempts`` gates count these."""
        with self._lock:
            self._attempts[instance] = self._attempts.get(instance, 0) + 1

    def _armed(self, instance: str, target: str, after: int) -> bool:
        if not self.cfg.enabled:
            return False
        if target and target not in instance:
            return False
        return self._attempts.get(instance, 0) > after

    def take_corruption(self, instance: str, stream_idx: int) -> bool:
        """One corrupt frame off the budget (called per frame send)."""
        with self._lock:
            fire = (self.cfg.corrupt_frames > 0
                    and self._armed(instance, self.cfg.corrupt_instance,
                                    self.cfg.corrupt_after_attempts)
                    and self.corruptions < self.cfg.corrupt_frames)
            if fire:
                self.corruptions += 1
        if fire:
            log.warning("transfer fault: corrupting a frame on stream %d "
                        "-> %s", stream_idx, instance)
        return fire

    def maybe_stall(self, instance: str, stream_idx: int) -> None:
        """Stall this stream before its first frame (interruptible)."""
        with self._lock:
            fire = (self.cfg.stall_s > 0
                    and self._armed(instance, self.cfg.stall_instance,
                                    self.cfg.stall_after_attempts)
                    and (self.cfg.stall_streams < 0
                         or self.stalls < self.cfg.stall_streams))
            if fire:
                self.stalls += 1
        if fire:
            log.warning("transfer fault: stalling stream %d -> %s for "
                        "%.1fs", stream_idx, instance, self.cfg.stall_s)
            self._stop.wait(self.cfg.stall_s)

    def take_control_kill(self, instance: str) -> bool:
        """One mid-round control-channel kill off the budget."""
        with self._lock:
            fire = (self.cfg.kill_control_rounds > 0
                    and self._armed(instance,
                                    self.cfg.kill_control_instance,
                                    self.cfg.kill_control_after_attempts)
                    and self.control_kills < self.cfg.kill_control_rounds)
            if fire:
                self.control_kills += 1
        if fire:
            log.warning("transfer fault: killing the control channel to "
                        "%s mid-round", instance)
        return fire


class FaultInjector:
    """Config-driven fault source; all counters are cumulative and public
    (tests and ``bench.py --chaos`` report them)."""

    def __init__(self, cfg: FaultInjectionConfig | None = None, **overrides):
        if cfg is None:
            cfg = FaultInjectionConfig(enabled=True, **overrides)
        elif overrides:
            cfg = dataclasses.replace(cfg, **overrides)
        self.cfg = cfg
        self._lock = threading.Lock()
        self._tokens: dict[str, int] = {}   # base rid -> streamed tokens
        self._killed: set[str] = set()
        self._stalled: set[str] = set()
        self._admitted = 0
        self._drained = False
        # pool drill: a zero-arg callable that kills one engine (e.g.
        # ``RolloutServer.kill`` or ``FakeEngine.kill``); armed by
        # engine_kill_times in the config
        self.engine_killer = None
        # spot-market hook (rollout/spotmarket.py): when a SpotMarket is
        # attached its fault/spot_* counters ride the same step record as
        # the fault/* recovery counters its events cause
        self.spot = None
        # telemetry
        self.kills = 0
        self.corruptions = 0
        self.stalls = 0
        self.drains = 0
        self.stream_kills = 0
        self.engine_kills = 0

    def counters(self) -> dict[str, float]:
        out = {
            "fault/injected_kills": float(self.kills),
            "fault/injected_corruptions": float(self.corruptions),
            "fault/injected_stalls": float(self.stalls),
            "fault/injected_drains": float(self.drains),
            "fault/injected_stream_kills": float(self.stream_kills),
            "fault/injected_engine_kills": float(self.engine_kills),
        }
        if self.spot is not None:
            out.update(self.spot.counters())
        return out

    # -- engine/server-side hooks -------------------------------------------

    def on_submit(self, server, rid: str, abort_event) -> None:
        """Called by ``RolloutServer.submit`` for every admission."""
        if not self.cfg.enabled:
            return
        trigger_drain = False
        with self._lock:
            self._admitted += 1
            if (self.cfg.drain_after_requests > 0 and not self._drained
                    and self._admitted >= self.cfg.drain_after_requests):
                self._drained = True
                trigger_drain = True
        if trigger_drain:
            self.drains += 1
            log.warning("fault injection: draining server after %d "
                        "admissions", self._admitted)
            server.drain()

    def on_line(self, rid: str, line: dict, abort_event) -> str | None:
        """Called by the server for every outgoing NDJSON line; returns a
        replacement raw string (corruption) or None to serialize normally.
        May set the abort event (kill) or sleep (stall) as a side effect."""
        if not self.cfg.enabled:
            return None
        key = base_rid(rid)
        n_tok = len(line.get("token_ids", ()))
        with self._lock:
            count = self._tokens.get(key, 0) + n_tok
            self._tokens[key] = count
            do_stall = (self.cfg.stall_s > 0 and key not in self._stalled
                        and count >= self.cfg.stall_after_tokens
                        and self._admitted >= self.cfg.stall_after_requests
                        and (self.cfg.stall_limit < 0
                             or self.stalls < self.cfg.stall_limit))
            if do_stall:
                self._stalled.add(key)
                self.stalls += 1
            do_corrupt = (self.cfg.corrupt_after_tokens > 0
                          and count >= self.cfg.corrupt_after_tokens
                          and self.corruptions < self.cfg.corrupt_limit)
            if do_corrupt:
                self.corruptions += 1
            do_kill = (self.cfg.kill_after_tokens > 0
                       and count >= self.cfg.kill_after_tokens
                       and abort_event is not None
                       and not (self.cfg.once_per_request
                                and key in self._killed)
                       and (self.cfg.kill_limit < 0
                            or self.kills < self.cfg.kill_limit))
            if do_kill:
                self._killed.add(key)
                self.kills += 1
        if do_stall:
            time.sleep(self.cfg.stall_s)
        if do_kill:
            log.warning("fault injection: killing %s after %d tokens",
                        rid, count)
            abort_event.set()
        if do_corrupt:
            # unparseable JSON: exercises the manager's decode-error
            # eviction path (stream_from_instance parse failure)
            return '{"token_ids": [!corrupted-by-fault-injection\n'
        return None

    # -- trainer/client-side hook -------------------------------------------

    def wrap_stream(self, stream, pending_rids: list[str]):
        """Wrap ``ManagerClient.batch_generate_stream``: pass items through,
        then raise a transport error once every still-pending rid has
        reported >= ``stream_kill_min_progress`` salvageable tokens — the
        worst-case manager death for the salvage ledger to recover from.

        With ``engine_kill_times`` armed, also fires the registered
        ``engine_killer`` once the stream has forwarded
        ``engine_kill_min_progress`` progress tokens: the engine dies
        provably mid-batch (SIGKILL semantics — no drain, no notice) and
        the pool must recover by heartbeat eviction + continuation."""
        arm_stream = self.cfg.enabled and self.cfg.stream_kill_times > 0
        arm_engine = (self.cfg.enabled and self.cfg.engine_kill_times > 0
                      and self.engine_killer is not None)
        if not arm_stream and not arm_engine:
            yield from stream
            return
        from polyrl_tpu.manager.client import (GenerateProgress,
                                               ManagerTransportError)

        progress = {r: 0 for r in pending_rids}
        total_progress = 0
        pending = set(pending_rids)
        for item in stream:
            if isinstance(item, GenerateProgress):
                if item.rid in progress:
                    progress[item.rid] += len(item.token_ids)
                    total_progress += len(item.token_ids)
            else:
                pending.discard(getattr(item, "rid", None))
            yield item
            kill_engine = False
            with self._lock:
                if (arm_engine
                        and self.engine_kills < self.cfg.engine_kill_times
                        and total_progress
                        >= self.cfg.engine_kill_min_progress):
                    self.engine_kills += 1
                    kill_engine = True
                armed = (arm_stream
                         and self.stream_kills < self.cfg.stream_kill_times)
                fire = (armed and pending
                        and all(progress[r] >= self.cfg.stream_kill_min_progress
                                for r in pending))
                if fire:
                    self.stream_kills += 1
            if kill_engine:
                log.warning("fault injection: killing an engine mid-batch "
                            "(%d rids pending)", len(pending))
                self.engine_killer()
            if fire:
                log.warning("fault injection: killing manager stream with "
                            "%d rids pending", len(pending))
                raise ManagerTransportError(
                    "fault injection: stream kill")
