"""Elastic rollout pool: N engines, one manager, preemption as a normal
event (ARCHITECTURE.md "Elastic pool").

The C++ manager owns the data plane — request routing (queue-depth- and
weight-version-aware, ``state.h next_instance``), heartbeat-timeout
eviction, and the weight-bootstrap gate that keeps a late joiner out of
the routing set until its weight version reaches the pool floor. This
module is the FLEET-side control plane on top of it:

- :class:`PoolManager` — membership lifecycle. ``add_engine`` registers a
  server (attaching its weight receiver so the transfer fabric's idle poll
  catches it up to the current version), ``preempt`` runs the scale-down
  drill (``POST /drain`` → salvaged partials re-route as suffix resumes on
  survivors → graceful deregistration), and ``sweep``/``wait_for_size``
  give tests, the bench ``--pool`` topology, and the trainer's /statusz a
  live membership view with ``pool/*`` counters.
- :class:`BalanceEstimator` — the paper's progressive train↔rollout
  balance estimator: a sliding window over recent steps' ``goodput/*``
  phase walls (generate vs update vs bubble) replaces the one-scalar feed
  the manager's hill-climbing balancer used to get, so one anomalous step
  (a preemption drill, a checkpoint) no longer yanks the colocated
  generation window around.

Scheduling reference: the Adaptive Placement framework (PAPERS.md);
trainer/fleet decoupling per LlamaRL (PAPERS.md).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
import urllib.request
from collections import deque
from statistics import median

from polyrl_tpu.obs.timeseries import least_squares_slope

log = logging.getLogger(__name__)


@dataclasses.dataclass
class PoolConfig:
    """``rollout.pool.*`` knobs (config.py RolloutSection)."""
    # expected pool size for launchers/bench --pool (0 = whatever joins)
    engines: int = 0
    # background membership sweep cadence (0 = manual sweep() only)
    sweep_interval_s: float = 0.0
    # scale-down drill: wait after /drain for abort partials to flush
    # through their open manager streams before deregistering
    drain_grace_s: float = 0.5
    # scale-up: how long add_engine(wait=True) waits for the engine to
    # pass health + the weight-bootstrap gate into the routing set
    join_deadline_s: float = 120.0
    # balance estimator sliding window (steps)
    balance_window: int = 8


def _http_post(endpoint: str, path: str, payload: dict | None = None,
               timeout: float = 5.0) -> dict:
    req = urllib.request.Request(
        f"http://{endpoint}{path}",
        data=json.dumps(payload or {}).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read() or b"{}")


def _http_get(endpoint: str, path: str, timeout: float = 3.0) -> dict:
    req = urllib.request.Request(f"http://{endpoint}{path}", method="GET")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read() or b"{}")


class PoolManager:
    """Fleet membership on top of a :class:`ManagerClient`.

    The manager's registry is the source of truth; this object adds the
    lifecycle verbs (join with weight catch-up, preemption drill, hard
    evict), a cached membership snapshot for /statusz, and cumulative
    ``pool/*`` counters for step records."""

    def __init__(self, manager, cfg: PoolConfig | None = None):
        self.manager = manager
        self.cfg = cfg or PoolConfig()
        self._lock = threading.Lock()
        self._last_status: dict = {}
        self._last_sweep = 0.0
        # drill bookkeeping (manager counters survive respawns via
        # /reconcile; these are the drills THIS control plane initiated)
        self.preemptions = 0
        self.hard_evictions = 0
        # weight-fabric escalations (ARCHITECTURE.md "Weight-fabric fault
        # tolerance"): engines drained + deregistered after exhausting
        # their push retry budget — dead capacity removed, not re-pushed
        self.laggards = 0
        # optional zero-arg callable returning the sender-side per-engine
        # sync health ({endpoint: {pushed_version, push_failures, ...}};
        # train.py wires TransferInterface.sync_health) — merged into the
        # /statusz pool section's engine rows as their "transfer" block
        self.transfer_health_fn = None
        # sweep fault isolation: transient manager HTTP errors are
        # counted (pool/sweep_failed) and backed off, never fatal to the
        # background sweep thread
        self.sweep_failures = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if self.cfg.sweep_interval_s > 0:
            self._thread = threading.Thread(target=self._sweep_loop,
                                            name="pool-sweep", daemon=True)
            self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- membership view ---------------------------------------------------

    def sweep(self) -> dict:
        """One /get_instances_status snapshot (cached for statusz readers);
        best-effort — a respawning manager returns the last good view."""
        try:
            st = self.manager.get_instances_status()
        except Exception:  # noqa: BLE001 — manager mid-respawn
            self.sweep_failures += 1
            log.warning("pool sweep failed; serving last snapshot",
                        exc_info=True)
            with self._lock:
                return dict(self._last_status)
        with self._lock:
            self._last_status = st
            self._last_sweep = time.monotonic()
        return st

    def _sweep_loop(self) -> None:
        # fault isolation: sweep() already swallows manager errors, but a
        # flaky manager must not spin the thread at full cadence either —
        # consecutive failures double the interval (capped at 8x), one
        # success restores it, and the loop NEVER exits on error
        base = self.cfg.sweep_interval_s
        interval = base
        while not self._stop.wait(interval):
            before = self.sweep_failures
            try:
                self.sweep()
            except Exception:  # noqa: BLE001 — belt and braces: nothing
                # a sweep raises may kill the membership view
                self.sweep_failures += 1
                log.warning("pool sweep raised; continuing", exc_info=True)
            interval = (min(interval * 2, base * 8)
                        if self.sweep_failures > before else base)

    def engines(self, refresh: bool = True) -> list[dict]:
        st = self.sweep() if refresh else self._last_status
        return list(st.get("instances", []))

    def active_count(self, refresh: bool = True) -> int:
        return sum(1 for i in self.engines(refresh)
                   if i.get("active", i.get("healthy")))

    def probe(self, endpoint: str) -> bool:
        """Direct serving-health probe of one engine (the manager's view
        lags one heartbeat tick; drills want the live answer)."""
        try:
            return _http_get(endpoint, "/health_generate").get(
                "status") == "ok"
        except Exception:  # noqa: BLE001 — dead/draining engines say no
            return False

    # -- scale-up ----------------------------------------------------------

    def add_engine(self, server=None, endpoint: str = "",
                   transfer_streams: int = 4, wait: bool = True,
                   deadline_s: float | None = None) -> str:
        """Join one engine mid-run. With a :class:`RolloutServer`, the
        weight receiver is attached too, so the transfer fabric's idle
        poll full-pushes the current version and the engine then rides the
        normal async push fan-out; the manager keeps it OUT of the routing
        set until its version reaches the pool floor (state.h
        promote_healthy / complete_weight_update). Returns the endpoint."""
        if server is not None:
            from polyrl_tpu.rollout.serve import register_with_manager

            register_with_manager(server, client=self.manager,
                                  transfer_streams=transfer_streams)
            endpoint = server.endpoint
        elif endpoint:
            self.manager.register_rollout_instance(endpoint)
        else:
            raise ValueError("add_engine needs a server or an endpoint")
        if wait:
            self.wait_for_member(endpoint,
                                 deadline_s or self.cfg.join_deadline_s)
        return endpoint

    def wait_for_member(self, endpoint: str, deadline_s: float = 120.0,
                        active: bool = True) -> dict:
        """Poll until ``endpoint`` is in the routing set (or merely
        registered+healthy with ``active=False``)."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            for inst in self.engines():
                if inst.get("endpoint") != endpoint:
                    continue
                if inst.get("active") if active else inst.get("healthy"):
                    return inst
            time.sleep(0.1)
        raise TimeoutError(
            f"engine {endpoint} not {'active' if active else 'healthy'} "
            f"after {deadline_s:.0f}s: {self.engines(refresh=False)}")

    def wait_for_size(self, n: int, deadline_s: float = 60.0) -> None:
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            if self.active_count() >= n:
                return
            time.sleep(0.1)
        raise TimeoutError(f"pool never reached {n} active engines: "
                           f"{self.engines(refresh=False)}")

    # -- scale-down --------------------------------------------------------

    def preempt(self, endpoint: str, grace_s: float | None = None) -> dict:
        """Scale-down as a drill, not a disaster: ``POST /drain`` (the
        engine refuses new admissions and aborts in-flight requests into
        salvageable partials, which re-route to survivors as suffix
        resumes through the manager's continuation), a short grace for
        those aborts to flush, then graceful deregistration.

        Against an ALREADY-DEAD endpoint the drain POST fails: there are
        no partials to flush, so the grace sleep is skipped and the
        removal falls through to the hard-eviction path idempotently —
        booked ONCE as an eviction (not a graceful departure), never a
        raise (the heartbeat backstops a failed deregister too)."""
        self.preemptions += 1
        out: dict = {}
        drained = True
        try:
            out = _http_post(endpoint, "/drain")
        except Exception:  # noqa: BLE001 — engine may already be gone
            drained = False
            log.warning("drain of %s failed; evicting instead",
                        endpoint, exc_info=True)
        if not drained:
            self.hard_evictions += 1
            try:
                self.manager.deregister_rollout_instance(endpoint,
                                                         drained=False)
            except Exception:  # noqa: BLE001 — heartbeat backstops
                log.warning("eviction of %s failed; heartbeat will evict",
                            endpoint, exc_info=True)
            return out
        time.sleep(grace_s if grace_s is not None else self.cfg.drain_grace_s)
        try:
            self.manager.deregister_rollout_instance(endpoint, drained=True)
        except Exception:  # noqa: BLE001 — heartbeat eviction backstops
            log.warning("deregister of %s failed; heartbeat will evict",
                        endpoint, exc_info=True)
        return out

    def evict(self, endpoint: str) -> None:
        """Hard removal (the drill for death WITHOUT notice — normally the
        manager's heartbeat does this on its own)."""
        self.hard_evictions += 1
        self.manager.deregister_rollout_instance(endpoint, drained=False)

    def escalate_laggard(self, endpoint: str, reason: str = "") -> None:
        """Weight-fabric escalation (``SenderAgent.laggard_cb``): this
        engine exhausted its push retry budget — its weights can never
        catch up, the bootstrap gate already holds it out of routing, and
        until now it was re-pushed every ``poll_s`` forever. Drain it
        (best-effort: salvageable partials re-route to survivors) and
        deregister, booking an eviction — it is dead capacity, not a
        graceful departure."""
        self.laggards += 1
        log.error("pool: escalating laggard %s (%s) — drain + deregister",
                  endpoint, reason or "push retry budget exhausted")
        try:
            _http_post(endpoint, "/drain")
        except Exception:  # noqa: BLE001 — it may be fully dead already
            log.warning("laggard drain of %s failed; deregistering anyway",
                        endpoint, exc_info=True)
        try:
            self.manager.deregister_rollout_instance(endpoint,
                                                     drained=False)
        except Exception:  # noqa: BLE001 — heartbeat eviction backstops
            log.warning("laggard deregister of %s failed; heartbeat will "
                        "evict", endpoint, exc_info=True)

    # -- telemetry ---------------------------------------------------------

    def counters(self, refresh: bool = True) -> dict[str, float]:
        """``pool/*`` + fleet ``engine/*`` gauges for step records / bench
        lines. The engine gauges aggregate the flight-deck telemetry the
        manager's stats poller forwards per instance: mean + min decode
        occupancy (a collapse on ONE engine must be visible in the fleet
        view), worst page-pool pressure, worst latency tails, summed
        throughput — the step-record feed the FlightRecorder watches."""
        st = self.sweep() if refresh else dict(self._last_status)
        pool = st.get("pool", {})
        insts = st.get("instances", [])
        out = {
            "pool/engines": float(pool.get("registered", len(insts))),
            "pool/active": float(pool.get("active", 0)),
            "pool/pending": float(pool.get("pending", 0)),
            "pool/joins": float(pool.get("joins", 0)),
            "pool/evictions": float(pool.get("evictions", 0)),
            "pool/drain_departures": float(pool.get("drain_departures", 0)),
            "pool/preemption_drills": float(self.preemptions),
            "pool/laggard_escalations": float(self.laggards),
            "pool/sweep_failed": float(self.sweep_failures),
        }
        versions = [int(i.get("weight_version", -1)) for i in insts]
        if versions:
            out["pool/weight_version_floor"] = float(min(versions))
        out.update(self._fleet_engine_gauges(insts))
        return out

    @staticmethod
    def _fleet_engine_gauges(insts: list[dict]) -> dict[str, float]:
        """Fleet-wide ``engine/*`` aggregates over the instances reporting
        flight-deck telemetry (engines predating it are skipped, not
        counted as zeros — a joining v0 engine must not fake a collapse)."""
        rep = [i for i in insts
               if i.get("healthy") and "occupancy" in i]
        if not rep:
            return {}
        occ = [float(i.get("occupancy", 0.0)) for i in rep]
        out = {
            "engine/occupancy": sum(occ) / len(occ),
            "engine/occupancy_min": min(occ),
            "engine/page_util": max(float(i.get("page_util", 0.0))
                                    for i in rep),
            "engine/ttft_p95_s": max(float(i.get("ttft_p95_s", 0.0))
                                     for i in rep),
            "engine/tpot_p95_s": max(float(i.get("tpot_p95_s", 0.0))
                                     for i in rep),
            "engine/cache_hit_rate": (
                sum(float(i.get("cache_hit_rate", 0.0)) for i in rep)
                / len(rep)),
            "engine/throughput_tok_s": sum(
                float(i.get("last_gen_throughput", 0.0)) for i in rep),
            "engine/attributed_frac_min": min(
                float(i.get("attributed_frac", 1.0)) for i in rep),
            # group-shared prefill: fleet-mean fraction of prompt tokens
            # served from shared/cached pages, and the request-level
            # (length-unbiased) prefix hit fraction
            "engine/prefill_reuse_frac": (
                sum(float(i.get("prefill_reuse_frac", 0.0)) for i in rep)
                / len(rep)),
            "engine/prefix_hit_frac": (
                sum(float(i.get("prefix_hit_frac", 0.0)) for i in rep)
                / len(rep)),
            # shared-prefix decode attention: fleet-mean HBM pages streamed
            # per decoded token and the fraction of logical KV reads the
            # grouped kernel deduplicated (the decode-bandwidth A/B signal)
            "engine/kv_read_pages_per_token": (
                sum(float(i.get("kv_read_pages_per_token", 0.0))
                    for i in rep) / len(rep)),
            "engine/shared_prefix_read_frac": (
                sum(float(i.get("shared_prefix_read_frac", 0.0))
                    for i in rep) / len(rep)),
        }
        # KV memory plane (rollout/kvledger.py) — worst-case semantics:
        # the coldest engine is the one the spill/autoscale tiers act on,
        # the tightest HBM headroom is the one that OOMs first. Per-field
        # presence guard: engines with the ledger off (or predating it)
        # are skipped, not counted as 0 cold / 0 headroom.
        cold = [float(i["kv_cold_page_frac"]) for i in rep
                if "kv_cold_page_frac" in i]
        if cold:
            out["engine/kv_cold_page_frac"] = max(cold)
        heads = [float(i["hbm_headroom_gb"]) for i in rep
                 if "hbm_headroom_gb" in i]
        if heads:
            out["engine/hbm_headroom_gb"] = min(heads)
        # host-RAM spill tier (rollout/kvspill.py) — worst case again: the
        # engine with the most KV paged out (frac can exceed 1.0 under
        # oversubscription) and the hottest restore churn (thrash signal)
        spilled = [float(i["kv_spilled_frac"]) for i in rep
                   if "kv_spilled_frac" in i]
        if spilled:
            out["engine/kv_spilled_frac"] = max(spilled)
        restores = [float(i["kv_restore_rate"]) for i in rep
                    if "kv_restore_rate" in i]
        if restores:
            out["engine/kv_restore_rate"] = max(restores)
        # engine-loop profiler (obs/engine_profile.py) — the fleet's
        # weakest link again: the LOWEST device_frac is the engine whose
        # loop thread is burning the most host wall per device second
        # (the disaggregation steering signal), the HIGHEST
        # accounting_frac the first to trip the overhead budget. Presence
        # guard: engines with loop_profile off (or predating it) are
        # skipped, not counted as 0.
        device = [float(i["device_frac"]) for i in rep
                  if "device_frac" in i]
        if device:
            out["engine/device_frac"] = min(device)
        acct = [float(i["accounting_frac"]) for i in rep
                if "accounting_frac" in i]
        if acct:
            out["engine/accounting_frac"] = max(acct)
        host = [float(i["host_overhead_frac"]) for i in rep
                if "host_overhead_frac" in i]
        if host:
            out["engine/host_overhead_frac"] = max(host)
        return out

    def engine_section(self) -> dict:
        """The trainer-side /statusz ``engine`` block: the fleet aggregate
        plus the per-engine flight-deck view (served from the cached sweep
        — the exporter never blocks on a respawning manager). Since v8 it
        carries the ``loop`` block (the fleet view of the engine-loop
        profiler) like the rollout plane does."""
        with self._lock:
            insts = list(dict(self._last_status).get("instances", []))
        fleet = {k.split("/", 1)[1]: round(v, 6)
                 for k, v in self._fleet_engine_gauges(insts).items()}
        return {
            "fleet": fleet,
            "engines": [{
                "endpoint": i.get("endpoint", ""),
                "occupancy": float(i.get("occupancy", 0.0)),
                "page_util": float(i.get("page_util", 0.0)),
                "ttft_p95_s": float(i.get("ttft_p95_s", 0.0)),
                "tpot_p95_s": float(i.get("tpot_p95_s", 0.0)),
                "cache_hit_rate": float(i.get("cache_hit_rate", 0.0)),
                "spec_accept_rate": float(i.get("spec_accept_rate", 0.0)),
                "attributed_frac": float(i.get("attributed_frac", 1.0)),
                "prefill_reuse_frac": float(
                    i.get("prefill_reuse_frac", 0.0)),
                "kv_read_pages_per_token": float(
                    i.get("kv_read_pages_per_token", 0.0)),
                "shared_prefix_read_frac": float(
                    i.get("shared_prefix_read_frac", 0.0)),
                "throughput_tok_s": float(i.get("last_gen_throughput", 0.0)),
                "kv_cold_page_frac": float(i.get("kv_cold_page_frac", 0.0)),
                # engine-loop profiler split (presence-guarded: the
                # manager only forwards them when the engine reports)
                **({"device_frac": float(i["device_frac"])}
                   if "device_frac" in i else {}),
                **({"accounting_frac": float(i["accounting_frac"])}
                   if "accounting_frac" in i else {}),
                "running": int(i.get("num_running_reqs", 0)),
            } for i in insts if "occupancy" in i],
            "loop": self.loop_profile_section(),
        }

    def loop_profile_section(self) -> dict:
        """The fleet view of the engine-loop profiler (statusz v8
        ``engine.loop`` on the trainer plane, and the FlightRecorder's
        ``engine_profile_fn`` → ``engine_profile.json`` bundle artifact):
        worst-case device/accounting split + the per-engine rows, served
        from the cached sweep. ``{"enabled": false}`` when no engine
        reports the profiler fields (loop_profile off fleet-wide, or
        engines predating it)."""
        with self._lock:
            insts = list(dict(self._last_status).get("instances", []))
        rep = [i for i in insts
               if i.get("healthy") and "device_frac" in i]
        if not rep:
            return {"enabled": False}
        return {
            "enabled": True,
            "engines_reporting": len(rep),
            "device_frac_min": round(
                min(float(i["device_frac"]) for i in rep), 6),
            "accounting_frac_max": round(
                max(float(i.get("accounting_frac", 0.0)) for i in rep), 6),
            "engines": [{
                "endpoint": i.get("endpoint", ""),
                "device_frac": float(i["device_frac"]),
                "accounting_frac": float(i.get("accounting_frac", 0.0)),
            } for i in rep],
        }

    def memory_section(self) -> dict:
        """The trainer-side /statusz ``memory`` block (and the
        FlightRecorder's ``memory_fn`` view): fleet worst-case KV
        residency + HBM headroom plus the per-engine rows, served from
        the cached sweep. Empty when no engine reports the ledger fields
        (ledger off fleet-wide, or engines predating it)."""
        with self._lock:
            insts = list(dict(self._last_status).get("instances", []))
        rep = [i for i in insts
               if i.get("healthy") and "kv_cold_page_frac" in i]
        if not rep:
            return {}
        fleet: dict = {
            "engines_reporting": len(rep),
            "kv_cold_page_frac_max": max(
                float(i["kv_cold_page_frac"]) for i in rep),
        }
        heads = [float(i["hbm_headroom_gb"]) for i in rep
                 if "hbm_headroom_gb" in i]
        if heads:
            fleet["hbm_headroom_gb_min"] = min(heads)
        spilled = [float(i["kv_spilled_frac"]) for i in rep
                   if "kv_spilled_frac" in i]
        if spilled:
            fleet["kv_spilled_frac_max"] = max(spilled)
        restores = [float(i["kv_restore_rate"]) for i in rep
                    if "kv_restore_rate" in i]
        if restores:
            fleet["kv_restore_rate_max"] = max(restores)
        return {
            "fleet": fleet,
            "engines": [{
                "endpoint": i.get("endpoint", ""),
                "kv_cold_page_frac": float(i["kv_cold_page_frac"]),
                **({"hbm_headroom_gb": float(i["hbm_headroom_gb"])}
                   if "hbm_headroom_gb" in i else {}),
                **({"kv_spilled_frac": float(i["kv_spilled_frac"])}
                   if "kv_spilled_frac" in i else {}),
                **({"kv_restore_rate": float(i["kv_restore_rate"])}
                   if "kv_restore_rate" in i else {}),
            } for i in rep],
        }

    def statusz_section(self) -> dict:
        """The /statusz ``pool`` block: membership + per-engine health,
        queue depth, weight version, and — with the transfer fabric
        attached — each engine's weight-sync health (pushed version, push
        failures, verify rejections, resume bytes, laggard flag), all
        served from the cached sweep so the exporter never blocks on a
        respawning manager."""
        with self._lock:
            st = dict(self._last_status)
            age = time.monotonic() - self._last_sweep if self._last_sweep \
                else -1.0
        sync: dict = {}
        if self.transfer_health_fn is not None:
            try:
                sync = dict(self.transfer_health_fn() or {})
            except Exception:  # noqa: BLE001 — health is best-effort
                log.warning("transfer sync-health probe failed",
                            exc_info=True)
        return {
            "counts": {k.split("/", 1)[1]: v
                       for k, v in self.counters(refresh=False).items()},
            "engines": [{
                "transfer": sync.get(i.get("endpoint", ""), {}),
                "endpoint": i.get("endpoint", ""),
                "is_local": bool(i.get("is_local")),
                "healthy": bool(i.get("healthy")),
                "active": bool(i.get("active")),
                "draining": bool(i.get("draining")),
                "weight_version": int(i.get("weight_version", -1)),
                "running": int(i.get("num_running_reqs", 0)),
                "queued": int(i.get("num_queued_reqs", 0)),
                "heartbeat_misses": int(i.get("heartbeat_misses", 0)),
                # flight-deck load view (0.0 for engines predating it)
                "occupancy": float(i.get("occupancy", 0.0)),
                "page_util": float(i.get("page_util", 0.0)),
                # sharded-push receive plane (receiver health): how many
                # parallel push streams this engine accepts per round and
                # its advertised tp shard count (1 = unsharded install)
                "push_streams": int(i.get("transfer_push_streams", 0)),
                "shard_tp": int(i.get("transfer_shard_tp", 1)),
            } for i in st.get("instances", [])],
            "snapshot_age_s": round(age, 3),
        }


class BalanceEstimator:
    """Progressive train↔rollout balance estimator.

    The manager's hill-climbing balancer (balance.h) actuates the
    colocated generation window from three scalars per step. Before this
    estimator those scalars were the LAST step's raw values, so one
    anomalous step (preemption drill, checkpoint write, a salvage resume
    wait) would swing the window by gap/3 off a measurement that says
    nothing about steady state. This maintains a sliding window of recent
    steps' goodput phase walls and feeds the balancer per-field MEDIANS —
    the same robust-baseline trick tools/bench_gate.py uses — plus
    ``pool/balance_*`` gauges so the step record shows what the balancer
    actually saw."""

    def __init__(self, window: int = 8):
        self.window = max(1, int(window))
        self._steps: deque[dict[str, float]] = deque(maxlen=self.window)
        self._lock = threading.Lock()

    def observe(self, *, step_time_s: float = 0.0,
                trainer_bubble_s: float = 0.0, throughput: float = 0.0,
                generate_s: float = 0.0, update_s: float = 0.0,
                occupancy: float = 0.0, device_frac: float = 0.0,
                **_ignored) -> None:
        """Fold one finished step in. ``generate_s``/``update_s`` are the
        goodput ledger's phase walls (timing_s/gen and the actor+critic
        update phases); ``occupancy`` the fleet-mean ``engine/occupancy``
        gauge (one step of lag — the sweep that produced it preceded this
        record); ``device_frac`` the fleet-MIN engine-loop profiler
        device fraction (same lag) — a fleet that looks busy by
        occupancy but is burning its wall host-side instead of on the
        device should not read as "add engines". Extra keys are accepted
        and ignored so callers can pass a whole stats dict through."""
        with self._lock:
            self._steps.append({
                "step_time_s": float(step_time_s),
                "trainer_bubble_s": float(trainer_bubble_s),
                "throughput": float(throughput),
                "generate_s": float(generate_s),
                "update_s": float(update_s),
                "occupancy": float(occupancy),
                "device_frac": float(device_frac),
            })

    def _window_median(self, key: str) -> float:
        return median(s[key] for s in self._steps) if self._steps else 0.0

    def trends(self) -> dict[str, float]:
        """Per-step least-squares slopes over the window — the
        balance-driven autoscaling input (ROADMAP: act on PoolManager
        add/drain). A rising occupancy slope with a rising bubble slope
        reads "the fleet is saturating and the trainer is starting to
        starve: add an engine"; both falling reads "drain one". Keys:
        ``{occupancy,bubble,step_time,throughput}_slope`` +
        ``window_steps`` + ``balance_trends_valid``; {} before the first
        observe.

        Cold-window guard: a least-squares slope over 1-2 points is
        noise (two points ALWAYS fit a line exactly), so with fewer than
        3 observed steps every slope is forced to 0.0 and
        ``balance_trends_valid`` is 0.0 — the AutoscaleController
        suppresses trend-driven actions until the window is real."""
        with self._lock:
            if not self._steps:
                return {}
            steps = list(self._steps)
        xs = list(range(len(steps)))
        valid = len(steps) >= 3

        def slope(key: str) -> float:
            if not valid:
                return 0.0
            return least_squares_slope(xs, [s[key] for s in steps])

        return {
            "occupancy_slope": slope("occupancy"),
            "bubble_slope": slope("trainer_bubble_s"),
            "step_time_slope": slope("step_time_s"),
            "throughput_slope": slope("throughput"),
            # engine-loop profiler feed: a falling fleet device_frac with
            # a rising occupancy reads "the engines are host-bound, not
            # device-bound — more engines won't help"
            "device_frac_slope": slope("device_frac"),
            "window_steps": float(len(steps)),
            "balance_trends_valid": 1.0 if valid else 0.0,
        }

    def stats(self) -> dict[str, float]:
        """Smoothed balancer feed (the update_metrics payload). Falls back
        to zeros before the first observe — the manager then keeps its
        initial window."""
        with self._lock:
            if not self._steps:
                return {}
            return {
                "step_time_s": self._window_median("step_time_s"),
                "trainer_bubble_s": self._window_median("trainer_bubble_s"),
                "throughput": self._window_median("throughput"),
            }

    def metrics(self) -> dict[str, float]:
        """``pool/balance_*`` step-record gauges: what the balancer saw,
        plus the estimated offload fraction — the share of generation the
        trainer-side update window can NOT hide, i.e. what should run on
        remote engines rather than the colocated one."""
        with self._lock:
            if not self._steps:
                return {}
            gen = self._window_median("generate_s")
            upd = self._window_median("update_s")
            bubble = self._window_median("trainer_bubble_s")
            step = self._window_median("step_time_s")
            device = self._window_median("device_frac")
        gen_total = gen + bubble  # colocated gen + blocked-on-remote time
        offload = gen_total / (gen_total + upd) if gen_total + upd > 0 else 0.0
        trends = self.trends()
        return {
            "pool/balance_window_steps": float(len(self._steps)),
            "pool/balance_step_time_s": step,
            "pool/balance_bubble_s": bubble,
            "pool/balance_generate_s": gen,
            "pool/balance_update_s": upd,
            "pool/balance_offload_frac": offload,
            # trend gauges (the autoscaling inputs): windowed per-step
            # slopes of fleet occupancy and the trainer bubble
            "pool/balance_occupancy_slope": trends.get(
                "occupancy_slope", 0.0),
            "pool/balance_bubble_slope": trends.get("bubble_slope", 0.0),
            # windowed fleet-min engine-loop device fraction (what the
            # balancer saw, not one sweep's snapshot)
            "pool/balance_device_frac": device,
            "pool/balance_trends_valid": trends.get(
                "balance_trends_valid", 0.0),
        }
