"""Rollout: inference engine + step decoder + HTTP serving layer."""

from .engine import GenerationOutput, RolloutEngine
from .sampling import SamplingParams
from .stepper import StepDecoder

__all__ = ["GenerationOutput", "RolloutEngine", "SamplingParams", "StepDecoder"]
