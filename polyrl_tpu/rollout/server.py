"""HTTP rollout server: the TPU-native replacement for the reference's
patched SGLang server (SURVEY §2.2 L2 surface; launch path §3.2).

Speaks exactly the protocol the C++ manager consumes:
- POST /generate                 — streaming NDJSON, one line per token with
                                   token_ids + logprobs + finish_reason
                                   (reference handlers.rs:152-328)
- GET  /health, /health_generate — registration-time health gate
                                   (instance_manager.rs:5-37)
- GET  /get_server_info          — queue-depth + throughput telemetry
                                   (patches.py:423-425)
- POST /abort_request            — mid-decode abort (local time-slicing,
                                   handlers.rs:500-513)
- POST /update_weights_from_agent— load pushed weights from the receiver
                                   buffer into the live engine
                                   (patches.py:137-357)
- POST /release|resume_memory_occupation, /flush_cache, /shutdown

Serving model: requests land in an admission queue; a batching loop groups
compatible requests (same sampling group) into bucketed batches and drives
``StepDecoder.generate_stream``, fanning tokens out to per-request queues —
a continuous-batching-lite scheduler (full paged/continuous batching is the
planned upgrade, SURVEY §7 step 2).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import jax
import numpy as np

from polyrl_tpu import obs
from polyrl_tpu.rollout.cb_engine import STREAM_END
from polyrl_tpu.rollout.flightdeck import ThroughputEWMA
from polyrl_tpu.rollout.sampling import SamplingParams
from polyrl_tpu.rollout.stepper import StepDecoder

log = logging.getLogger(__name__)

# one terminal marker shared with the CB engine so either backend can feed
# the same per-request output queues
_SENTINEL = STREAM_END


@dataclasses.dataclass
class _PendingRequest:
    rid: str
    input_ids: list[int]
    sampling: SamplingParams
    out: queue.Queue
    abort: threading.Event


class RolloutServer:
    """Wraps a RolloutEngine + StepDecoder behind the manager protocol."""

    def __init__(self, engine, host: str = "0.0.0.0", port: int = 0,
                 max_batch: int | None = None, batch_wait_s: float = 0.01,
                 advertise_host: str = "127.0.0.1"):
        self.engine = engine
        # backend dispatch: a CBEngine admits requests itself (continuous
        # batching); the v0 RolloutEngine is driven through StepDecoder by
        # this server's grouping batch loop
        self.cb = hasattr(engine, "submit")
        self.stepper = None if self.cb else StepDecoder(engine)
        self.max_batch = max_batch or max(getattr(engine, "batch_buckets", (64,)))
        self.batch_wait_s = batch_wait_s
        # v0 batch-loop throughput smoothing (the CB engine smooths its
        # own): one fast/slow batch must not alias heartbeat samplers
        self._tput_ewma = ThroughputEWMA()
        self._queue: "queue.Queue[_PendingRequest]" = queue.Queue()
        self._aborts: dict[str, threading.Event] = {}
        self._aborts_lock = threading.Lock()
        self._stop = threading.Event()
        self._paused = threading.Event()  # release_memory_occupation
        # graceful preemption (POST /drain): in-flight requests abort into
        # PARTIALS (salvage-enabled engines flush decoded tokens first) and
        # new submissions are refused with an immediate abort terminal so
        # the manager's continuation re-routes them. One-way by design —
        # a drained server is about to lose its host.
        self._draining = threading.Event()
        self.drain_count = 0  # requests aborted by /drain (telemetry)
        # chaos kill switch (pool drills): a "SIGKILLed" engine answers
        # nothing and breaks every open stream mid-chunk — no drain, no
        # partial flush, exactly the wire signature of a dead process.
        # The manager's heartbeat then evicts it and in-flight rids
        # continue on survivors through the salvage path.
        self._killed = threading.Event()
        # manager this server registered with (serve.register_with_manager
        # / PoolManager.add_engine) — the leave/preempt lifecycle notifies
        # it on graceful departure; "" = never registered
        self.manager_endpoint = ""
        # optional FaultInjector (rollout/faults.py): observes admissions
        # and every outgoing stream line; can kill/corrupt/stall/drain
        self.fault = None
        self.receiver = None  # ReceiverAgent, attached by serve.py
        # quantized serving (models/quant.py): the wire format stays the
        # trainer's bf16 tree — weight_template carries that tree's
        # structure for layout/unflatten, weight_preprocess re-quantizes
        # each arriving push before the device swap. weight_apply (LoRA
        # delta sync) instead REPLACES the whole install step: it maps
        # (current engine params, received tree) -> new engine params —
        # adapter pushes touch only the a/b leaves, never the base.
        self.weight_template = None
        self.weight_preprocess = None
        self.weight_apply = None
        # a streamed round's clock starts BEFORE the trainer's pack, so the
        # receive wait gets the combined pack+wire budget (matches the
        # sender's stream_push_timeout_s)
        self.weight_sync_timeout_s = 3600.0
        self._weight_lock = threading.Lock()
        self._loop_thread: threading.Thread | None = None
        # fleet time-series rail (obs/timeseries.py): every server_info()
        # sample lands in the per-key ring under engine/* — the manager's
        # stats poller sets the cadence — and /statusz serves the windowed
        # aggregates + slopes as the "timeseries" section
        self._timeseries = obs.TimeSeriesStore()
        self._ts_samples = 0

        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, obj: dict) -> None:
                self._send(code, json.dumps(obj).encode(), "application/json")

            def do_GET(self):
                if outer._killed.is_set():
                    self.close_connection = True
                    self.connection.close()
                    return
                if self.path == "/health":
                    self._json(200, {"status": "ok"})
                elif self.path == "/health_generate":
                    # a draining server is alive but must not pass the
                    # manager's serving health gate
                    if outer._draining.is_set():
                        self._json(503, {"status": "draining"})
                    else:
                        self._json(200, {"status": "ok"})
                elif self.path == "/get_server_info":
                    self._json(200, outer.server_info())
                elif self.path == "/statusz":
                    # live health plane: the SAME JSON schema the trainer's
                    # exporter serves (obs/statusz.py), so one parser
                    # sweeps both planes
                    self._json(200, outer.statusz_snapshot())
                elif self.path == "/metrics":
                    # Prometheus text exposition of the same telemetry the
                    # manager polls via /get_server_info (plus the engine's
                    # POLYRL_CB_TRACE phase timers when enabled)
                    self._send(200, outer.metrics_text().encode(),
                               "text/plain; version=0.0.4")
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if outer._killed.is_set():
                    self.close_connection = True
                    self.connection.close()
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if self.path == "/generate":
                    self.handle_generate(body)
                elif self.path == "/preempt":
                    # preemption notice (the cloud's "you have N seconds"):
                    # ack first, then run the drain + graceful leave off
                    # the handler thread so the notifier is never blocked
                    self._json(200, {"success": True, "draining": True})
                    threading.Thread(target=outer.leave, daemon=True).start()
                elif self.path == "/update_weights_from_agent":
                    ok, err = outer.update_weights_from_agent(
                        int(body.get("weight_version", -1)))
                    self._json(200 if ok else 500,
                               {"success": ok, "error": err})
                elif self.path == "/abort_request":
                    outer.abort_request(body.get("rid"))
                    self._json(200, {"success": True})
                elif self.path == "/drain":
                    self._json(200, outer.drain())
                elif self.path == "/flush_cache":
                    self._json(200, {"success": True})
                elif self.path == "/release_memory_occupation":
                    outer.release_memory()
                    self._json(200, {"success": True})
                elif self.path == "/resume_memory_occupation":
                    outer.resume_memory()
                    self._json(200, {"success": True})
                elif self.path == "/shutdown":
                    self._json(200, {"success": True})
                    threading.Thread(target=outer.stop, daemon=True).start()
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def handle_generate(self, body: dict) -> None:
                rid = str(body.get("rid", f"req-{time.monotonic_ns()}"))
                input_ids = [int(t) for t in body.get("input_ids", [])]
                sp = SamplingParams.from_dict(body.get("sampling_params", {}))
                # group-shared prefill hint (GRPO: rollout_n samples of one
                # prompt dispatched together): the engine prefills the
                # shared prompt ONCE and batch-attaches the siblings.
                # Optional fields — absent/zero degrades to per-request
                # admission, never corrupts.
                group_id = str(body.get("group_id", "") or "")
                group_size = int(body.get("group_size", 0) or 0)
                # cross-process trace adoption: the manager injects the
                # trainer's (trace_id, span_id) into the forwarded request,
                # so this engine span joins the trainer's trace — the last
                # hop of trainer→manager→engine
                trace_ctx = None
                if body.get("trace_id"):
                    trace_ctx = (str(body["trace_id"]),
                                 str(body.get("parent_span") or ""))
                tracer = obs.get_tracer()
                with tracer.adopt(trace_ctx), \
                        tracer.span("engine/generate", rid=rid):
                    self._stream_generate(rid, input_ids, sp,
                                          group_id, group_size)

            def _stream_generate(self, rid, input_ids, sp,
                                 group_id="", group_size=0) -> None:
                out_q, abort_ev = outer.submit(rid, input_ids, sp,
                                               group_id=group_id,
                                               group_size=group_size)

                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(line: str) -> None:
                    data = line.encode()
                    self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                    self.wfile.flush()

                try:
                    done = False
                    while not done:
                        items = [out_q.get()]
                        # drain the burst: a multi-step dispatch fetch
                        # delivers k lines at once — one chunked write per
                        # burst instead of k write+flush syscall pairs
                        try:
                            while True:
                                items.append(out_q.get_nowait())
                        except queue.Empty:
                            pass
                        # truncate at the FIRST sentinel: failure paths can
                        # enqueue lines after a sentinel (e.g. a batch-wide
                        # error after a row already finished) and a
                        # sentinel object must never reach json.dumps
                        for i, it in enumerate(items):
                            if it is _SENTINEL:
                                items = items[:i]
                                done = True
                                break
                        if items:
                            chunk("".join(outer._serialize_line(rid, i,
                                                                abort_ev)
                                          for i in items))
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    outer.abort_request(rid)
                finally:
                    outer._drop_abort(rid, abort_ev)

        # default request_queue_size (listen backlog) is 5: a burst of
        # concurrent clients (the manager fanning a batch out) gets
        # connection resets before accept() ever runs
        server_cls = type("_RolloutHTTPServer", (ThreadingHTTPServer,),
                          {"request_queue_size": 1024})
        self._http = server_cls((host, port), Handler)
        self.port = self._http.server_address[1]
        self.endpoint = f"{advertise_host}:{self.port}"

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "RolloutServer":
        if self.cb:
            self.engine.start()
        else:
            self._loop_thread = threading.Thread(target=self._batch_loop, daemon=True)
            self._loop_thread.start()
        threading.Thread(target=self._http.serve_forever, daemon=True).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.cb:
            self.engine.stop()
        if self.receiver is not None:
            self.receiver.stop()
        self._http.shutdown()

    # -- request admission & batching loop ----------------------------------

    def submit(self, rid: str, input_ids: list[int],
               sp: SamplingParams, group_id: str = "",
               group_size: int = 0) -> tuple[queue.Queue, threading.Event]:
        """Admit one request; returns (output queue, abort event). The
        caller that registered the abort event must pass it back to
        ``_drop_abort`` — cleanup is identity-checked so a retry that
        re-used the rid cannot have its fresh event popped by the dying
        first attempt's teardown. ``group_id``/``group_size`` are the
        group-shared-prefill hint forwarded to the CB engine."""
        out: queue.Queue = queue.Queue()
        abort = threading.Event()
        if self._draining.is_set():
            # graceful preemption: refuse with a partial-abort terminal —
            # the manager's continuation layer re-routes the request
            out.put({"token_ids": [], "logprobs": [], "finished": True,
                     "finish_reason": "abort"})
            out.put(_SENTINEL)
            return out, abort
        # Duplicate in-flight rid: usually a manager retry racing the dying
        # first attempt (its handler thread drops the rid only after seeing
        # BrokenPipe on the next write). Abort the stale entry and give it a
        # short grace to clear before rejecting — a second registration
        # sharing the rid would orphan the first request's abort event.
        deadline = time.monotonic() + 2.0
        while True:
            with self._aborts_lock:
                stale = self._aborts.get(rid)
                if stale is None:
                    self._aborts[rid] = abort
                    break
                stale.set()
            if time.monotonic() >= deadline:
                out.put({"token_ids": [], "logprobs": [], "finished": True,
                         "finish_reason": "error",
                         "error": f"duplicate rid {rid!r} in flight"})
                out.put(_SENTINEL)
                return out, abort
            time.sleep(0.01)
        if self.fault is not None:
            self.fault.on_submit(self, rid, abort)
        if self._draining.is_set():
            # drain landed between the admission check and event
            # registration: its abort sweep missed this event — trip it
            # ourselves so the engine aborts the request into a partial
            abort.set()
        if self.cb:
            self.engine.submit(rid, input_ids, sp, out=out, abort=abort,
                               group_id=group_id, group_size=group_size)
        else:
            self._queue.put(_PendingRequest(rid, input_ids, sp, out, abort))
        return out, abort

    def abort_request(self, rid: str | None) -> None:
        """Abort one request, or ALL running requests when rid is None/'' —
        the manager's local time-slice abort (handlers.rs:500-513)."""
        with self._aborts_lock:
            if rid:
                ev = self._aborts.get(rid)
                if ev is not None:
                    ev.set()
            else:
                for ev in self._aborts.values():
                    ev.set()

    def drain(self) -> dict:
        """POST /drain — graceful preemption: stop admitting (new requests
        get an immediate partial-abort terminal), fail the serving health
        gate, and abort every in-flight request. With a salvage-enabled
        engine each abort flushes the tokens decoded so far as a partial,
        so the manager's continuation (or the trainer's salvage ledger)
        resumes them on another instance from the last token instead of
        re-decoding from zero."""
        self._draining.set()
        with self._aborts_lock:
            n = len(self._aborts)
        self.drain_count += n
        self.abort_request(None)
        return {"success": True, "draining": True, "aborted": n}

    def leave(self, grace_s: float = 0.5) -> None:
        """Graceful pool departure (POST /preempt, or a launcher's SIGTERM
        handler): drain — in-flight requests flush salvageable partials
        that re-route to surviving engines — then tell the manager this
        endpoint is gone so the routing set shrinks NOW instead of at the
        next heartbeat tick. Best-effort on the notify: the heartbeat is
        the backstop."""
        self.drain()
        time.sleep(grace_s)  # let abort partials flush through open streams
        if not self.manager_endpoint:
            return
        try:
            import urllib.request

            req = urllib.request.Request(
                f"http://{self.manager_endpoint}/deregister_rollout_instance",
                data=json.dumps({"endpoint": self.endpoint,
                                 "drained": True}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5.0):
                pass
        except Exception:  # noqa: BLE001 — heartbeat eviction backstops
            log.warning("deregister with manager %s failed",
                        self.manager_endpoint, exc_info=True)

    def kill(self) -> None:
        """Chaos: die WITHOUT notice. No drain, no salvage flush, no
        manager notify — open streams break mid-chunk, new connections are
        dropped, and the listener closes. Recovery is entirely the pool's
        job (heartbeat eviction + manager continuation on survivors).
        ``stop()`` still owns the eventual resource teardown."""
        self._killed.set()
        # wake blocked handler threads: their next queue item hits the
        # killed check in _serialize_line and breaks the connection
        self.abort_request(None)
        threading.Thread(target=self._http.shutdown, daemon=True).start()

    def _serialize_line(self, rid: str, line: dict, abort_ev) -> str:
        """One outgoing NDJSON line; the fault injector may replace it
        (corruption), delay it (stall), or trip the abort event (kill)."""
        if self._killed.is_set():
            # dead engines don't speak: break the stream mid-chunk, exactly
            # where a SIGKILLed process would have
            raise BrokenPipeError("engine killed (chaos)")
        if self.fault is not None:
            replaced = self.fault.on_line(rid, line, abort_ev)
            if replaced is not None:
                return replaced
        return json.dumps(line) + "\n"

    def _drop_abort(self, rid: str, ev: threading.Event | None = None) -> None:
        with self._aborts_lock:
            if ev is None or self._aborts.get(rid) is ev:
                self._aborts.pop(rid, None)

    def _batch_loop(self) -> None:
        # requests pulled but not matching the current batch's sampling
        # group wait here and are served FIRST next round (no starvation
        # behind a sustained stream of another group)
        held: list[_PendingRequest] = []
        while not self._stop.is_set():
            if held:
                first = held.pop(0)
            else:
                try:
                    first = self._queue.get(timeout=0.2)
                except queue.Empty:
                    continue
            if self._paused.is_set():
                # engine yielded HBM to the trainer: wait for resume
                held.insert(0, first)
                time.sleep(0.05)
                continue
            batch = [first]
            deadline = time.monotonic() + self.batch_wait_s
            key = first.sampling.group_key()
            matched, unmatched = [], []
            for req in held:
                (matched if req.sampling.group_key() == key else unmatched).append(req)
            batch.extend(matched[: self.max_batch - 1])
            held = unmatched + matched[self.max_batch - 1 :]
            while len(batch) < self.max_batch:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                try:
                    req = self._queue.get(timeout=left)
                except queue.Empty:
                    break
                if req.sampling.group_key() == key:
                    batch.append(req)
                else:
                    held.append(req)
            try:
                self._run_batch(batch)
            except Exception as exc:  # noqa: BLE001 — fail the whole batch
                log.exception("batch failed")
                for req in batch:
                    req.out.put({"token_ids": [], "logprobs": [],
                                 "finished": True, "finish_reason": "error",
                                 "error": str(exc)})
                    req.out.put(_SENTINEL)

    def _run_batch(self, batch: list[_PendingRequest]) -> None:
        t0 = time.monotonic()
        self.engine.num_running = len(batch)
        prompts = [r.input_ids for r in batch]
        limits = [r.sampling.max_new_tokens for r in batch]
        flags = [r.abort for r in batch]
        total = 0
        closed = [False] * len(batch)
        with self._weight_lock:
            # tag each chunk with the weight version that sampled it: the
            # whole batch runs under _weight_lock, so one capture suffices
            wv = self.engine.weight_version
            stream = self.stepper.generate_stream(
                prompts, batch[0].sampling, max_new=limits, abort_flags=flags)
            for ev in stream:
                req = batch[ev["row"]]
                if ev["token"] is None:  # abort without a token this step
                    req.out.put({"token_ids": [], "logprobs": [],
                                 "finished": True, "finish_reason": "abort"})
                else:
                    total += 1
                    req.out.put({
                        "token_ids": [ev["token"]],
                        "logprobs": [ev["logprob"]],
                        "finished": ev["done"],
                        "finish_reason": ev["finish_reason"],
                        "weight_version": wv,
                    })
                if ev["done"]:
                    req.out.put(_SENTINEL)
                    closed[ev["row"]] = True
        # defense in depth: every handler MUST see a sentinel or it blocks
        # its HTTP thread forever
        for req, done in zip(batch, closed):
            if not done:
                req.out.put({"token_ids": [], "logprobs": [], "finished": True,
                             "finish_reason": "error",
                             "error": "stream ended without completion"})
                req.out.put(_SENTINEL)
        dt = time.monotonic() - t0
        self.engine.last_gen_throughput = self._tput_ewma.update(
            total / dt if dt > 0 else 0.0)
        self.engine.num_running = 0

    # -- telemetry / weights / memory ---------------------------------------

    def server_info(self) -> dict:
        info = {
            "num_running_reqs": self.engine.num_running,
            "num_queued_reqs": (self.engine.num_queued if self.cb
                                else self._queue.qsize()),
            "last_gen_throughput": self.engine.last_gen_throughput,
            "weight_version": self.engine.weight_version,
            # preemption announcement: the manager's heartbeat reads this
            # and pulls a draining engine out of the routing set before the
            # next batch routes to it
            "draining": self._draining.is_set(),
        }
        pc = getattr(self.engine, "prefix_cache", None)
        if pc is not None:
            info.update(pc.stats())
            # flat request-level hit fraction (length-unbiased, unlike
            # hit_rate which counts pages): flat key so the manager's
            # stats poller can forward it per instance
            info["prefix_hit_frac"] = round(pc.request_hit_frac, 6)
        if hasattr(self.engine, "admit_wave"):
            # admission scheduler geometry + group-shared prefill counters
            # (ARCHITECTURE.md "Group-shared prefill"): the knobs are
            # echoed so bench/statusz record what the scheduler actually
            # ran with; the dispatch counters are what the --group-share
            # A/B reads (dispatch count bounds admission throughput)
            info["admit_wave"] = self.engine.admit_wave
            info["admit_reorder_window"] = self.engine.admit_reorder_window
            info["group_share"] = bool(self.engine.group_share)
            # shared-prefix decode attention: the kernel-side group-share
            # switch + the pre-ref TTL knob echo (both config-driven, so
            # bench/statusz record what the engine actually ran with), and
            # the grouped-dispatch counter the --decode-attn A/B reads
            info["decode_group_share"] = bool(
                getattr(self.engine, "decode_group_share", False))
            info["group_preref_ttl_s"] = float(
                getattr(self.engine, "group_preref_ttl_s", 0.0))
            info["grouped_decode_dispatches"] = int(
                getattr(self.engine, "grouped_decode_dispatches", 0))
            info["prefill_dispatches"] = self.engine.prefill_dispatches
            info["sibling_attach_dispatches"] = (
                self.engine.sibling_attach_dispatches)
            info["group_forked_requests"] = self.engine.group_forked_requests
        # partial-rollout salvage telemetry (cb engine); drained requests
        # are a server-level count (the /drain preemption path)
        if getattr(self.engine, "salvage_partials", False):
            info["tokens_salvaged"] = self.engine.tokens_salvaged
            info["salvage_published_pages"] = (
                self.engine.salvage_published_pages)
        if self.drain_count:
            info["drained_requests"] = self.drain_count
        if getattr(self.engine, "spec_tokens", 0):
            # speculative acceptance telemetry: emitted/dispatch vs the
            # spec_tokens+1 ceiling says whether the lookup is paying off
            info["spec_emitted"] = self.engine.spec_emitted
            info["spec_dispatches"] = self.engine.spec_dispatches
            info["spec_accept_rate"] = round(
                getattr(self.engine, "spec_accept_rate", 0.0), 4)
        deck = getattr(self.engine, "deck", None)
        if deck is not None:
            # engine flight deck: occupancy / page pressure / server-side
            # TTFT+TPOT tails / token-accounting reconciliation — flat keys
            # the manager's stats poller forwards and bench reads
            info.update(deck.server_info_fields())
        loop_info = getattr(self.engine, "loop_profile_info", None)
        if loop_info is not None:
            # engine-loop profiler (obs/engine_profile.py): the windowed
            # device-vs-host split as flat keys — the manager's stats
            # poller forwards device_frac / accounting_frac per instance,
            # bench's cb phase promotes them, and the engine/* time-series
            # feed below picks them up ({} when rollout.loop_profile=false)
            info.update(loop_info())
        kv_info = getattr(self.engine, "kv_memory_info", None)
        if kv_info is not None:
            # KV memory plane (rollout/kvledger.py): residency tiers, the
            # ledger↔pool reconciliation gauge, HBM truth, and the host
            # spill tier's kv_spilled_frac / kv_restore_rate — flat keys so
            # the manager's stats poller forwards kv_cold_page_frac /
            # hbm_headroom_gb / kv_spilled_frac per instance
            # ({} when rollout.kv_ledger=false)
            info.update(kv_info())
        if self.receiver is not None:
            # weight-sync health (transfer/agents.py ReceiverAgent.health):
            # control-channel reconnects, rejected CRC frames, verify
            # failures, resume bytes — a flapping sender or a corrupting
            # link is visible per engine in server_info and /statusz
            health = getattr(self.receiver, "health", None)
            if health is not None:
                info.update(health())
        # time-series sample: the numeric fields land in the engine/* ring
        # (sample index as x — occupancy/queue-depth slopes over the
        # poller's cadence, not the trainer's step clock)
        self._ts_samples += 1
        self._timeseries.observe(self._ts_samples, {
            "engine/" + k: v for k, v in info.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)})
        return info

    def statusz_snapshot(self) -> dict:
        """The rollout plane's side of the shared /statusz schema
        (ARCHITECTURE.md "Goodput & health plane"): engine queue depths,
        decode throughput, weight version, salvage/drain/fault-injection
        counters — one curl answers "what is this engine doing"."""
        from polyrl_tpu.obs import statusz

        info = self.server_info()
        counters = {k: float(v) for k, v in info.items()
                    if k in ("tokens_salvaged", "salvage_published_pages",
                             "drained_requests", "spec_emitted",
                             "spec_dispatches", "prefill_dispatches",
                             "sibling_attach_dispatches",
                             "group_forked_requests",
                             "grouped_decode_dispatches")}
        counters["total_tokens_served"] = float(
            getattr(self.engine, "total_tokens_served", 0))
        if self.fault is not None:
            counters.update(self.fault.counters())
        gauges = {k: float(v) for k, v in info.items()
                  if isinstance(v, (int, float))
                  and not isinstance(v, bool) and k not in counters}
        gauges["draining"] = float(self._draining.is_set())
        gauges["paused"] = float(self._paused.is_set())
        deck = getattr(self.engine, "deck", None)
        engine_section = {}
        if deck is not None:
            engine_section = deck.snapshot(
                active=int(info.get("num_running_reqs", 0)),
                queued=int(info.get("num_queued_reqs", 0)))
            if getattr(self.engine, "spec_tokens", 0):
                engine_section["spec"] = {
                    "accept_rate": float(info.get("spec_accept_rate", 0.0)),
                    "emitted": int(self.engine.spec_emitted),
                    "dispatches": int(self.engine.spec_dispatches),
                }
            if hasattr(self.engine, "admit_wave"):
                # group-shared prefill: scheduler geometry + fork counters
                # (the "did sharing actually happen" answer for one curl)
                engine_section["group"] = {
                    "admit_wave": int(self.engine.admit_wave),
                    "admit_reorder_window": int(
                        self.engine.admit_reorder_window),
                    "group_share": bool(self.engine.group_share),
                    "decode_group_share": bool(
                        getattr(self.engine, "decode_group_share", False)),
                    "group_preref_ttl_s": float(
                        getattr(self.engine, "group_preref_ttl_s", 0.0)),
                    "prefill_dispatches": int(self.engine.prefill_dispatches),
                    "sibling_attach_dispatches": int(
                        self.engine.sibling_attach_dispatches),
                    "group_forked_requests": int(
                        self.engine.group_forked_requests),
                    "grouped_decode_dispatches": int(getattr(
                        self.engine, "grouped_decode_dispatches", 0)),
                    "prefill_reuse_frac": float(
                        info.get("prefill_reuse_frac", 0.0)),
                    "prefix_hit_frac": float(
                        info.get("prefix_hit_frac", 0.0)),
                    # shared-prefix decode attention: streamed-vs-logical
                    # KV page dedup (the bandwidth actually saved)
                    "kv_read_pages_per_token": float(
                        info.get("kv_read_pages_per_token", 0.0)),
                    "shared_prefix_read_frac": float(
                        info.get("shared_prefix_read_frac", 0.0)),
                }
        # engine-loop profiler block: ALWAYS present in the engine section
        # since v8 (even with the deck off / non-cb engines) so consumers
        # never need existence checks — {"enabled": false} when off
        loop_snap = getattr(self.engine, "loop_profile_snapshot", None)
        engine_section["loop"] = (loop_snap() if loop_snap is not None
                                  else {"enabled": False})
        kv_snap = getattr(self.engine, "kv_memory_snapshot", None)
        return statusz.build_snapshot(
            "rollout",
            counters=counters, gauges=gauges,
            queues={"running": float(info.get("num_running_reqs", 0)),
                    "queued": float(info.get("num_queued_reqs", 0))},
            weights={"version": float(self.engine.weight_version)},
            engine=engine_section,
            timeseries=self._timeseries.section(),
            # KV memory plane (v6): per-page roles/tiers/churn + the
            # reconciliation block ({} for non-cb engines / ledger off)
            memory=kv_snap() if kv_snap is not None else None)

    def metrics_text(self) -> str:
        """Prometheus text format for /metrics: server_info fields as
        gauges, cumulative values (tokens served, engine trace counts +
        phase seconds) as counters. Full precision — %g-style rounding
        makes rate() over large counters see flat-then-jump."""

        def fmt(v):
            return str(int(v)) if float(v).is_integer() else repr(float(v))

        lines = []
        info = dict(self.server_info())
        info.setdefault("total_tokens_served",
                        getattr(self.engine, "total_tokens_served", 0))
        for k, v in info.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            name = "polyrl_" + k.replace("#", "num_").replace("/", "_")
            kind = "counter" if k == "total_tokens_served" else "gauge"
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {fmt(v)}")
        trace = getattr(self.engine, "trace_report", lambda: {})()
        for k, v in sorted(trace.items()):
            # every trace entry is cumulative (call counts and phase
            # seconds both only increase)
            name = f"polyrl_engine_{k}"
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {fmt(v)}")
        return "\n".join(lines) + "\n"

    def _flush_engine_prefix_cache(self) -> None:
        """Cached prefix KV was computed under the OLD weights/adapters; any
        disaggregated install path must invalidate it, exactly like
        in-process swaps do (cb_engine.update_weights flushes for the same
        reason). The bucketed v0 engine has no prefix cache — no-op there."""
        flush = getattr(self.engine, "flush_prefix_cache", None)
        if flush is not None:
            flush()

    def update_weights_from_agent(self, version: int) -> tuple[bool, str]:
        """Load weights v``version`` from the receiver buffer into the live
        engine (TPU analogue of the reference's chunked host->GPU broadcast
        load, patches.py:169-241: here one sharded device_put, GSPMD handles
        distribution)."""
        if self.receiver is None:
            # in-process updates (colocated): trainer calls
            # engine.update_weights directly; just ack the version
            self.engine.weight_version = version
            return True, ""
        try:
            from polyrl_tpu.transfer.layout import (
                make_incremental_installer, make_sharded_installer,
                unflatten_like, unpack_params,
            )

            template = (self.weight_template if self.weight_template
                        is not None else self.engine.params)
            if self.weight_apply is None and self.weight_preprocess is None:
                # full-tree bf16 path: upload each tensor AS ITS BYTES LAND
                # (wire || device_put — the receive-side half of the
                # streaming sync pipeline). Delta/int8 installs transform
                # the assembled tree, so they keep the post-wire path.
                # dtype/sharding come from the LIVE tree (template may be
                # abstract ShapeDtypeStructs), matching the serial path's
                # tree_map over engine.params. tp>1 engines take the
                # SHARDED installer: each leaf lands shard-by-shard via
                # per-device device_put + assembly, so the full-size
                # device array never materializes on one chip.
                if getattr(self.engine, "mesh", None) is not None:
                    install, device_named = make_sharded_installer(
                        self.engine.params)
                else:
                    install, device_named = make_incremental_installer(
                        self.engine.params)
                # record the version actually INSTALLED: when a
                # superseding round's bytes landed instead, reporting the
                # older requested version would under-report until the
                # newer push's own update call (advisor r4)
                installed = self.receiver.wait_for_version(
                    version, timeout=self.weight_sync_timeout_s,
                    on_tensor=install)
                if installed is None:  # pre-r5 receiver contract
                    installed = version
                new_params = unflatten_like(template, device_named)
                with self._weight_lock:  # not mid-batch
                    self.engine.params = new_params
                    self.engine.weight_version = installed
                    self._flush_engine_prefix_cache()
                return True, ""
            installed = self.receiver.wait_for_version(
                version, timeout=self.weight_sync_timeout_s)
            if installed is None:  # pre-r5 receiver contract
                installed = version
            named = unpack_params(self.receiver.buffer, self.receiver.layout)
            new_params = unflatten_like(template, named)
            if self.weight_apply is not None:
                # delta sync: the received tree is NOT full params (e.g.
                # LoRA adapters) — the hook installs it into the current
                # tree itself, device-putting only what changed
                with self._weight_lock:
                    self.engine.params = self.weight_apply(
                        self.engine.params, new_params)
                    self.engine.weight_version = installed
                    self._flush_engine_prefix_cache()
                return True, ""
            if self.weight_preprocess is not None:
                new_params = self.weight_preprocess(new_params)
            with self._weight_lock:  # not mid-batch
                old = self.engine.params
                self.engine.params = jax.tree_util.tree_map(
                    lambda o, n: jax.device_put(
                        np.asarray(n).astype(o.dtype), o.sharding), old,
                    new_params)
                self.engine.weight_version = installed
                self._flush_engine_prefix_cache()
            return True, ""
        except Exception as exc:  # noqa: BLE001
            log.exception("weight load failed")
            return False, str(exc)

    def release_memory(self) -> None:
        self._paused.set()
        self.engine.release_memory()

    def resume_memory(self) -> None:
        self.engine.resume_memory()
        self._paused.clear()
