"""RemoteRollout — the trainer-side adapter for disaggregated generation.

TPU-native equivalent of the reference's C5 ``SGLangRolloutRemote`` +
C7 ``StreamingBatchIterator`` (``sglang_rollout_remote.py:227-508``,
``stream_batch_iter.py:19-103``): the trainer hands it the unrolled prompt
batch (n samples per prompt); it streams the batch through the manager's
``/batch_generate_requests`` NDJSON endpoint and yields *complete prompt
groups* as soon as they finish — at least ``min_emit`` trajectories per
yield — so training on early ibatches overlaps generation of later ones
(the streaming overlap that is PolyRL's core idea, SURVEY.md §3.1).

Group integrity: GRPO/RLOO advantages are group-relative, so a group whose
members are split across ibatches would silently normalize against a
partial group. Groups are emitted whole; a group containing a permanently
failed request (manager exhausted its 5 continuation retries) is dropped
with a warning — the trainer's stream accounting tolerates a short batch.

Weight push rides the transfer fabric (C10-C13 equivalents in
``polyrl_tpu.transfer``): ``update_weights`` bumps the manager's weight
version (draining the active pool) and hands the params to the sender
agent, returning the new version.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import queue
import random
import threading
import time
from typing import Any, Iterator

import numpy as np

from polyrl_tpu import obs
from polyrl_tpu.manager.client import (ControlPlaneDown, GenerateProgress,
                                       GenerateResult, ManagerClient,
                                       ManagerTransportError)
from polyrl_tpu.rollout.pool import BalanceEstimator
from polyrl_tpu.rollout.sampling import SamplingParams

log = logging.getLogger(__name__)


class _SalvageLedger:
    """Per-rid token progress across manager stream attempts (token-level
    continuous generation).

    ``base_*`` — tokens already folded into the re-issued request's prompt
    (the salvaged prefix the target engine prefills instead of re-decoding);
    ``cur_*`` — progress streamed since the last re-issue, folded into base
    on the next failure. The terminal :class:`GenerateResult` of the CURRENT
    request repeats cur's tokens authoritatively, so the stitched sequence
    is always ``base + result`` — never ``base + cur + result``."""

    __slots__ = ("base_t", "base_l", "base_v", "cur_t", "cur_l", "cur_v")

    def __init__(self):
        self.base_t: list[int] = []
        self.base_l: list[float] = []
        self.base_v: list[int] = []
        self.cur_t: list[int] = []
        self.cur_l: list[float] = []
        self.cur_v: list[int] = []

    def extend_cur(self, prog: GenerateProgress) -> None:
        self.cur_t += [int(t) for t in prog.token_ids]
        self.cur_l += [float(x) for x in prog.logprobs]
        self.cur_v += [int(prog.weight_version)] * len(prog.token_ids)

    def fold(self) -> int:
        """Move cur into base (a re-issue is about to carry it in the
        prompt); returns how many tokens were newly salvaged."""
        n = len(self.cur_t)
        self.base_t += self.cur_t
        self.base_l += self.cur_l
        self.base_v += self.cur_v
        self.cur_t, self.cur_l, self.cur_v = [], [], []
        return n

    def stitch(self, res: GenerateResult) -> GenerateResult:
        """Prepend the salvaged prefix to a terminal result."""
        if not self.base_t or not res.success:
            return res
        wvs: list[int] = []
        if self.base_v or res.output_token_weight_versions:
            wvs = self.base_v + (res.output_token_weight_versions
                                 or [-1] * len(res.output_token_ids))
        return dataclasses.replace(
            res,
            output_token_ids=self.base_t + res.output_token_ids,
            output_token_logprobs=self.base_l + res.output_token_logprobs,
            output_token_weight_versions=wvs)


class RemoteRollout:
    def __init__(
        self,
        manager: ManagerClient,
        transfer=None,               # TransferInterface (trainer-side fabric)
        local_server=None,           # colocated RolloutServer (time-sliced)
        pad_token_id: int = 0,
        resume_budget: int = 3,      # mid-stream re-issues per batch
        resume_wait_s: float = 60.0,  # per-resume wait for manager recovery
        salvage_partials: bool = True,  # token-level suffix resume
        fault_injector=None,         # rollout/faults.py (tests, bench --chaos)
        balance_window: int = 8,     # progressive balance estimator window
        pool=None,                   # rollout/pool.py PoolManager (optional)
    ):
        self.manager = manager
        self.transfer = transfer
        self.local_server = local_server
        self.pad_token_id = pad_token_id
        self.resume_budget = resume_budget
        self.resume_wait_s = resume_wait_s
        self.salvage_partials = salvage_partials
        self.fault_injector = fault_injector
        self.weight_version = 0
        self.last_gen_throughput = 0.0
        self.dropped_groups = 0
        # control-plane fault counters (cumulative; trainer gauges them)
        self.stream_resumes = 0
        self.local_fallbacks = 0
        # requests completed by finish_locally (tier-2 degraded
        # completion): local_fallbacks counts the fallback EVENTS, this
        # counts the request volume those events had to finish on-host —
        # what the degradation plane sizes the cost of tier 2 with
        self.local_fallback_requests = 0
        # token-level salvage counters: tokens carried across a resume
        # instead of re-decoded, suffix re-issues performed, and the prefill
        # length those re-issues paid (prompt + salvage — the recovery cost
        # that replaces full re-decoding)
        self.tokens_salvaged = 0
        self.suffix_resumes = 0
        self.resume_prefill_tokens = 0
        # per-step manager /metrics scrape misses (telemetry degradation is
        # graceful: the merge is skipped, the step never fails — this
        # counter is the only trace a flaky scrape leaves)
        self.scrape_failures = 0
        # sample-looking /metrics lines that failed to parse (torn writes,
        # truncated responses): counted per scrape instead of silently
        # dropped (obs/scrape_partial)
        self.scrape_partials = 0
        # pool re-admissions of the colocated engine that stayed failed
        # past the retry budget: the pool silently lost its local engine
        # (it idles with restored KV HBM while the manager never routes to
        # it) — the counter is the visibility a log line never gave
        self.resume_instances_failures = 0
        # progressive train<->rollout balance estimator: update_metrics
        # feeds the manager's balancer windowed medians instead of the
        # last step's raw scalars (rollout/pool.py)
        self.balance = BalanceEstimator(window=balance_window)
        # optional fleet control plane (rollout/pool.py PoolManager): the
        # trainer merges its pool/* counters and /statusz section
        self.pool = pool
        # per-stream nonce keeps rids globally unique: concurrent streams
        # (nested REMAX baselines, validation overlapping training, and the
        # pipelined trainer's prefetch lane) would otherwise collide on
        # bare indices at the shared engines
        self._stream_seq = itertools.count()
        # time-slice refcount: with the pipelined trainer a validation
        # stream can overlap the prefetch lane's stream — the colocated
        # engine's KV HBM is resumed by the FIRST active stream and
        # released only when the LAST one ends (a per-stream release would
        # yank pages out from under the other stream's requests)
        self._ts_lock = threading.Lock()
        self._ts_active = 0

    def fault_counters(self) -> dict[str, float]:
        """Cumulative control-plane fault metrics (supervisor restarts,
        client retries, stream resumes/fallbacks, dropped groups)."""
        out = {
            "fault/stream_resumes": float(self.stream_resumes),
            "fault/local_fallbacks": float(self.local_fallbacks),
            "fault/local_fallback_requests": float(
                self.local_fallback_requests),
            "fault/dropped_groups": float(self.dropped_groups),
            "fault/tokens_salvaged": float(self.tokens_salvaged),
            "fault/suffix_resumes": float(self.suffix_resumes),
            "fault/resume_prefill_tokens": float(self.resume_prefill_tokens),
            "fault/resume_instances_failed": float(
                self.resume_instances_failures),
            "obs/scrape_failed": float(self.scrape_failures),
            "obs/scrape_partial": float(self.scrape_partials),
        }
        if self.fault_injector is not None:
            # chaos-mode visibility: the injected-fault counters ride the
            # same step-record gauges the recovery counters do, so a drill
            # record shows cause and effect side by side
            out.update(self.fault_injector.counters())
        transfer_counters = getattr(self.transfer, "counters", None)
        if transfer_counters is not None:
            # weight-fabric supervision (transfer/* gauges: push failures/
            # retries, verify rejections, resumed bytes, laggard
            # escalations, the sharded-push plane — push_streams,
            # stream_bw_mbps_min, reshard_bytes, stream_resumes — + knob
            # echo) — rides every step record, which is what the
            # FlightRecorder's transfer/push_failures watch reads
            out.update(transfer_counters())
        retries = getattr(self.manager, "retry_count", None)
        if retries is not None:
            out["fault/client_retries"] = float(retries)
        supervisor = getattr(self.manager, "supervisor", None)
        if supervisor is not None:
            out["fault/manager_restarts"] = float(supervisor.restarts)
        return out

    def _resume_local_instances(self, attempts: int = 3,
                                backoff_base_s: float = 0.1,
                                backoff_max_s: float = 1.0) -> bool:
        """Re-admit the colocated engine to the manager's routing set, with
        a bounded jittered-backoff retry. A one-shot call that swallowed
        its failure used to leave the pool silently one engine short — the
        local engine idled with restored KV HBM while every request went
        remote. Still best-effort past the budget (the stream must start
        even if the manager is mid-respawn), but the failure now lands in
        ``fault/resume_instances_failed`` so it is visible in step records
        instead of only in a log line."""
        err: Exception | None = None
        for attempt in range(attempts):
            try:
                self.manager.resume_local_instances()
                return True
            except Exception as exc:  # noqa: BLE001 — retried below
                err = exc
                if attempt + 1 < attempts:
                    sleep = min(backoff_base_s * 2 ** attempt,
                                backoff_max_s) * (0.5 + random.random())
                    time.sleep(sleep)
        self.resume_instances_failures += 1
        log.error("resume_local_instances failed after %d attempts "
                  "(%d total failures): %s", attempts,
                  self.resume_instances_failures, err)
        return False

    def _wait_manager_recovery(self) -> bool:
        """Poll /health until the manager answers (the supervisor respawn
        lands on a fresh port the client re-resolves) or the resume-wait
        budget expires."""
        deadline = time.monotonic() + self.resume_wait_s
        while time.monotonic() < deadline:
            if self.manager.health():
                return True
            time.sleep(0.25)
        return False

    # -- streaming generation ------------------------------------------------

    def generate_stream(
        self,
        prompt_ids: list[list[int]],
        sampling: SamplingParams,
        group_size: int,
        min_emit: int,
        max_local_gen_s: float | None = None,
        nested: bool = False,
    ) -> Iterator[list[tuple[int, GenerateResult]]]:
        """Yield lists of (original_index, result) covering whole groups,
        ≥ ``min_emit`` entries per yield (except the final remainder).
        Requests ``i*group_size .. (i+1)*group_size-1`` form group ``i``.
        ``min_emit`` need not divide by group_size — emission granularity is
        whole groups, the threshold just gates when to flush.

        ``nested=True`` marks a stream issued while an OUTER stream is still
        active (e.g. REMAX baselines mid-ibatch): it must not touch the
        colocated engine's resume/release lifecycle — release_memory would
        pause the local engine while the outer stream's requests are still
        being served on it."""
        assert len(prompt_ids) % group_size == 0
        # colocated time-slicing: the local engine serves during the window
        # (manager aborts it after max_local_gen_s, handlers.rs:500-513
        # equivalent), then yields its KV HBM back to training. Resume here,
        # release at window expiry (grace for the abort to drain) or at
        # stream end, whichever first.
        local_eng = (self.local_server.engine
                     if self.local_server is not None and not nested else None)
        released = threading.Event()

        def _release() -> None:
            # per-stream idempotent; the engine's KV HBM is only handed
            # back when the LAST concurrent stream releases (refcount)
            if released.is_set() or local_eng is None:
                return
            released.set()
            with self._ts_lock:
                self._ts_active -= 1
                last = self._ts_active == 0
            if not last:
                return
            try:
                local_eng.release_memory()
            except Exception:  # noqa: BLE001 — time-slicing is best-effort
                log.exception("local engine release_memory failed")

        window_timer: threading.Timer | None = None
        if local_eng is not None:
            with self._ts_lock:
                self._ts_active += 1
                first = self._ts_active == 1
            if first and hasattr(local_eng, "resume_memory"):
                local_eng.resume_memory()
            # re-admit time-sliced-out locals to the manager's active pool:
            # the watchdog removed them at the last window expiry
            # (handlers.rs:500-513), and engine resume + pool re-admission
            # must travel together or the pool starves while the engine
            # idles with restored KV HBM.
            self._resume_local_instances()
            if max_local_gen_s:
                window_timer = threading.Timer(max_local_gen_s + 1.0, _release)
                window_timer.daemon = True
                window_timer.start()
        stream_tag = f"s{next(self._stream_seq)}:"
        # group-shared prefill hint: requests i*G..(i+1)*G-1 share a prompt
        # (GRPO's n samples), so each carries a stream-unique group_id +
        # group_size. The manager pins a whole group to ONE engine (its
        # group-affinity routing) and the engine prefills the shared
        # prompt once, batch-attaching the siblings. group_size == 1
        # (validation/REMAX streams) sends no hint.
        reqs = [{"rid": f"{stream_tag}{i}", "input_ids": list(p),
                 **({"group_id": f"{stream_tag}g{i // group_size}",
                     "group_size": group_size} if group_size > 1 else {}),
                 "sampling_params": {
                     "temperature": sampling.temperature,
                     "top_p": sampling.top_p,
                     "top_k": sampling.top_k,
                     "max_new_tokens": sampling.max_new_tokens,
                     "stop_token_ids": list(sampling.stop_token_ids),
                 }}
                for i, p in enumerate(prompt_ids)]

        q: "queue.Queue[Any]" = queue.Queue()
        gen_t0 = time.monotonic()
        # completion timestamp taken in the reader thread: the consumer side
        # only resumes after trainer compute inside each yield, which would
        # inflate elapsed in exactly the overlapped mode this measures
        gen_end = [gen_t0]

        def finish_locally(pending: dict, ledger: dict) -> None:
            # last-resort degrade: the manager stayed down past the resume
            # budget but a colocated engine exists — finish the batch
            # in-process rather than losing it. The engine may have been
            # released by the window timer; resume for the fallback and
            # hand the HBM back afterwards if so. Requests were already
            # folded by fold_salvage, so their input_ids carry the salvaged
            # prefix and their max_new_tokens the remaining budget — the
            # degraded completion also resumes from the last token instead
            # of re-decoding from zero.
            eng = self.local_server.engine
            self.local_fallback_requests += len(pending)
            was_released = released.is_set()
            if hasattr(eng, "resume_memory"):
                eng.resume_memory()
            try:
                # group by remaining budget: eng.generate takes ONE
                # SamplingParams per call, and salvaged requests have
                # per-rid decremented budgets (no salvage → one group,
                # the pre-salvage behavior)
                by_budget: dict[int, list[dict]] = {}
                for r in pending.values():
                    mnt = int(r["sampling_params"].get(
                        "max_new_tokens", sampling.max_new_tokens))
                    by_budget.setdefault(mnt, []).append(r)
                for mnt, items in by_budget.items():
                    sp = dataclasses.replace(sampling, max_new_tokens=mnt)
                    outs = eng.generate([r["input_ids"] for r in items], sp)
                    for r, o in zip(items, outs):
                        if isinstance(o, dict):
                            ids, lps = o["token_ids"], o["logprobs"]
                            reason = o.get("finish_reason", "stop")
                        else:
                            ids = list(o.output_ids)
                            lps = list(o.output_token_logprobs)
                            reason = getattr(o, "finish_reason", "stop")
                        res = GenerateResult(
                            rid=r["rid"], success=reason != "error",
                            output_token_ids=[int(t) for t in ids],
                            output_token_logprobs=[float(x) for x in lps],
                            finish_reason=reason,
                            error="" if reason != "error" else "local fallback")
                        led = ledger.get(r["rid"])
                        q.put(led.stitch(res) if led is not None else res)
            finally:
                if was_released and hasattr(eng, "release_memory"):
                    try:
                        eng.release_memory()
                    except Exception:  # noqa: BLE001 — best-effort handback
                        log.exception("fallback release_memory failed")

        def fold_salvage(pending: dict, ledger: dict) -> None:
            """Token-level salvage after a stream failure: fold each pending
            rid's streamed progress into its request so the re-issue (or the
            local fallback) carries prompt+salvaged as the new prefill —
            hitting the target engine's prefix cache — with the token budget
            decremented. A rid whose salvaged prefix already hit a stop
            token or exhausted its budget is completed right here."""
            stops = set(sampling.stop_token_ids)
            for rid in list(pending):
                led = ledger.get(rid)
                if led is None:
                    continue
                req = pending[rid]
                sp = req["sampling_params"]
                n_new = led.fold()
                if n_new:
                    self.tokens_salvaged += n_new
                    req["input_ids"] = (list(req["input_ids"])
                                        + led.base_t[-n_new:])
                    sp["max_new_tokens"] = int(sp["max_new_tokens"]) - n_new
                if not led.base_t:
                    continue  # nothing salvaged: plain from-zero re-issue
                if led.base_t[-1] in stops or int(sp["max_new_tokens"]) <= 0:
                    # the salvage already completes the request — synthesize
                    # the terminal result instead of re-issuing
                    pending.pop(rid)
                    q.put(GenerateResult(
                        rid=rid, success=True,
                        output_token_ids=list(led.base_t),
                        output_token_logprobs=list(led.base_l),
                        finish_reason=("stop" if led.base_t[-1] in stops
                                       else "length"),
                        output_token_weight_versions=list(led.base_v)))
                    continue
                self.suffix_resumes += 1
                self.resume_prefill_tokens += len(req["input_ids"])

        def run_stream() -> None:
            # drains the NDJSON stream so the manager is never backpressured
            # by training compute (reference stream_batch_iter drain loop).
            # Stream-level resume: a mid-stream transport failure re-issues
            # ONLY the rids without a terminal result yet (completed ones
            # were already queued for group assembly) against the recovered
            # manager, at most resume_budget times. Token-level salvage
            # (salvage_partials): the manager forwards per-token progress
            # lines; a re-issued rid carries prompt+salvaged as its prompt
            # and the stitched result re-decodes NOTHING before the fault.
            pending = {r["rid"]: r for r in reqs}
            ledger: dict[str, _SalvageLedger] = (
                {r["rid"]: _SalvageLedger() for r in reqs}
                if self.salvage_partials else {})
            budget = self.resume_budget
            while pending:
                failure: ManagerTransportError | None = None
                try:
                    stream = self.manager.batch_generate_stream(
                        list(pending.values()),
                        max_local_gen_s=max_local_gen_s)
                    if self.fault_injector is not None:
                        stream = self.fault_injector.wrap_stream(
                            stream, list(pending))
                    for res in stream:
                        if isinstance(res, GenerateProgress):
                            led = ledger.get(res.rid)
                            if led is not None and res.rid in pending:
                                led.extend_cur(res)
                            continue
                        pending.pop(res.rid, None)
                        led = ledger.get(res.rid)
                        q.put(led.stitch(res) if led is not None else res)
                except ManagerTransportError as exc:
                    failure = exc
                if not pending:
                    return  # every rid got a terminal result
                if failure is None:
                    # the manager answers EVERY rid before ending the
                    # stream, so a "clean" end with rids missing is a
                    # truncated stream: a SIGKILLed manager closes the
                    # socket at a chunk boundary, which http.client reads
                    # as EOF, not as an error
                    failure = ManagerTransportError(
                        f"stream ended with {len(pending)} rids unanswered")
                if self.salvage_partials:
                    fold_salvage(pending, ledger)
                    if not pending:
                        return  # salvage completed every remaining rid
                log.warning(
                    "manager stream failed with %d/%d rids pending (%s); "
                    "attempting resume (%d left in budget)",
                    len(pending), len(reqs), failure, budget)
                recovered = False
                if budget > 0:
                    # recovery wait is attributable stall time: the goodput
                    # ledger maps the rollout/resume_wait_s totals into the
                    # salvage_resume phase
                    t_rw = time.monotonic()
                    recovered = self._wait_manager_recovery()
                    obs.observe("rollout/resume_wait_s",
                                time.monotonic() - t_rw)
                if recovered:
                    budget -= 1
                    self.stream_resumes += 1
                    continue
                if self.local_server is not None:
                    self.local_fallbacks += 1
                    log.warning("control plane down; finishing %d requests "
                                "on the colocated engine", len(pending))
                    finish_locally(pending, ledger)
                    return
                raise ControlPlaneDown(
                    f"manager unreachable after {self.resume_budget} stream "
                    f"resumes; {len(pending)} requests outstanding"
                ) from failure

        # trace hand-off: the reader drains in its own thread, so the span
        # context active HERE (the trainer's step span) is captured and
        # adopted there — the stream and its manager calls nest under the
        # step instead of starting orphan traces
        trace_ctx = obs.get_tracer().capture()

        def reader() -> None:
            try:
                with obs.get_tracer().adopt(trace_ctx), \
                        obs.span("rollout/stream", n=len(reqs)):
                    run_stream()
                gen_end[0] = time.monotonic()
                q.put(None)
            except Exception as exc:  # noqa: BLE001
                gen_end[0] = time.monotonic()
                q.put(exc)

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        n_tokens = 0

        groups: dict[int, list[tuple[int, GenerateResult]]] = {}
        failed_groups: set[int] = set()
        seen_rids: set[str] = set()
        pending: list[tuple[int, GenerateResult]] = []
        # try/finally: if the consumer abandons the generator or the stream
        # raises, the window timer must die and the colocated engine's KV
        # pool must still be handed back to training — leaking either starves
        # the trainer of HBM for the rest of the run.
        try:
            while True:
                item = q.get()
                if item is None:
                    break
                if isinstance(item, Exception):
                    raise item
                res: GenerateResult = item
                if res.rid in seen_rids:
                    # exactly-once guard across stream resumes: a result
                    # delivered just before the transport failure must not
                    # be double-counted if a re-issue races it
                    continue
                seen_rids.add(res.rid)
                idx = int(res.rid.rsplit(":", 1)[-1])
                g = idx // group_size
                if g in failed_groups:
                    continue
                if not res.success:
                    log.warning("group %d dropped: request %d failed: %s",
                                g, idx, res.error)
                    failed_groups.add(g)
                    groups.pop(g, None)
                    self.dropped_groups += 1
                    continue
                # per-request distribution telemetry (trainer-side view):
                # time from batch submission to this result, and the
                # request's effective decode rate over that window — the
                # tail the balancer reacts to, invisible in step averages
                lat = time.monotonic() - gen_t0
                obs.observe("rollout/latency_s", lat)
                if res.output_token_ids and lat > 0:
                    obs.observe("rollout/decode_tok_s",
                                len(res.output_token_ids) / lat)
                n_tokens += len(res.output_token_ids)
                groups.setdefault(g, []).append((idx, res))
                if len(groups[g]) == group_size:
                    pending.extend(sorted(groups.pop(g)))
                    if len(pending) >= min_emit:
                        yield pending
                        pending = []
            if groups:  # stream ended with incomplete groups (should not happen)
                log.warning("%d groups incomplete at stream end", len(groups))
                self.dropped_groups += len(groups)
            elapsed = gen_end[0] - gen_t0
            self.last_gen_throughput = n_tokens / elapsed if elapsed > 0 else 0.0
            if pending:
                yield pending
        finally:
            if window_timer is not None:
                window_timer.cancel()
            _release()  # stream done/abandoned: nothing left to serve locally

    # -- weight + metrics plane ----------------------------------------------

    def update_weights(self, params: Any, version: int | None = None) -> int:
        """Push new weights to every rollout instance through the fabric
        (§3.3 end-to-end). Falls back to a bare version bump when no fabric
        is attached (pure local serving)."""
        if self.transfer is not None:
            self.weight_version = self.transfer.update_weights_with_agent(params)
        else:
            self.weight_version = self.manager.update_weight_version()
        self._update_local_copy(params)
        return self.weight_version

    def update_weights_async(self, params: Any) -> int:
        """Non-blocking flavor for the pipelined trainer: the manager
        version bump (pool drain) and the colocated-engine copy happen
        inline — both are cheap and/or jax work that belongs on the
        trainer thread — while the fabric's pack/wire round completes in
        the background. ``wait_pushed()`` is the fence. Falls back to the
        synchronous push when no async-capable fabric is attached."""
        if self.transfer is None or not hasattr(self.transfer,
                                                "update_weights_async"):
            return self.update_weights(params)
        self.weight_version = self.transfer.update_weights_async(params)
        self._update_local_copy(params)
        return self.weight_version

    def wait_pushed(self, timeout: float = 600.0) -> None:
        """Block until every queued async push's pack round has landed;
        re-raises a background push failure. No-op with no fabric."""
        if self.transfer is not None and hasattr(self.transfer,
                                                 "wait_pushed"):
            self.transfer.wait_pushed(timeout)

    def push_lag(self) -> int:
        """Async push rounds issued but not yet landed on the fabric —
        the pipelined trainer's ``perf/staleness_lag`` gauge feed."""
        fn = getattr(self.transfer, "push_lag", None)
        return int(fn()) if fn is not None else 0

    def wait_push_lag(self, max_lag: int, timeout: float = 600.0) -> None:
        """Bounded-staleness admission gate (``trainer.staleness_limit``):
        block until at most ``max_lag`` pushes are in flight. Falls back
        to the full fence on fabrics without the lag surface."""
        fn = getattr(self.transfer, "wait_push_lag", None)
        if fn is not None:
            fn(max_lag, timeout)
        else:
            self.wait_pushed(timeout)

    def _update_local_copy(self, params: Any) -> None:
        if self.local_server is None:
            return
        # colocated engine shares the chip but must own a COPY: the
        # actor's opt step DONATES its param buffers while the engine
        # may still be serving late groups (streaming overlap) — a
        # by-reference swap leaves the engine on deleted buffers. The
        # reference pays the same cost (the local SGLang process holds
        # its own weights). No fabric hop either way; the manager
        # re-adds locals to the pool on update_weight_version.
        import jax
        import jax.numpy as jnp

        engine_copy = jax.tree_util.tree_map(jnp.copy, params)
        self.local_server.engine.update_weights(
            engine_copy, version=self.weight_version)

    def scrape_manager_metrics(self) -> dict[str, float]:
        """One scrape of the manager's GET /metrics, as ``manager/*`` gauge
        keys for the step record. Best-effort: a scrape miss (manager
        respawning, stub manager in tests) returns {}. Each scrape's wall
        latency lands in the ``manager/scrape_s`` histogram (a slow scrape
        on the pipeline lane delays the next stream's admission) and
        partially-parseable lines count into ``obs/scrape_partial``."""
        metrics_text = getattr(self.manager, "metrics_text", None)
        if metrics_text is None:
            return {}
        try:
            t0 = time.monotonic()
            gauges, partials = obs.manager_gauges_partial(metrics_text())
            obs.observe("manager/scrape_s", time.monotonic() - t0)
            self.scrape_partials += partials
            return gauges
        except Exception:  # noqa: BLE001 — telemetry must not fail a step
            # skip the merge, count the miss (obs/scrape_failed gauge via
            # fault_counters) — a respawning/flaky manager degrades the
            # step record, never the step or the pipeline lane
            self.scrape_failures += 1
            log.warning("manager /metrics scrape failed (%d total)",
                        self.scrape_failures, exc_info=True)
            return {}

    def update_metrics(self, **stats) -> dict:
        """Feed step stats to the manager's adaptive balancer; returns its
        response incl. the next local-generation budget (handlers.rs:867-901
        equivalent).

        The raw per-step stats first fold into the progressive balance
        estimator (``generate_s``/``update_s`` goodput phase walls ride
        along and stay trainer-side); the manager then receives the
        windowed medians — one anomalous step no longer swings the
        colocated generation window by gap/3."""
        self.balance.observe(**stats)
        smoothed = dict(stats)
        # estimator-only inputs never reach the wire
        smoothed.pop("generate_s", None)
        smoothed.pop("update_s", None)
        smoothed.pop("occupancy", None)
        smoothed.pop("device_frac", None)
        smoothed.update(self.balance.stats())
        try:
            return self.manager.update_metrics(**smoothed)
        except Exception:  # noqa: BLE001 — metrics are best-effort
            log.exception("update_metrics failed")
            return {}
