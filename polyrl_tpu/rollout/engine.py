"""Rollout engine v0: jitted prefill + while-loop decode with KV cache.

TPU-native stand-in for the reference's SGLang serving stack (SURVEY.md §2.2
row 1 — streaming ``/generate`` with ``output_token_logprobs``, weight
hot-swap via ``update_weights_from_tensor``, release/resume memory
occupation — reference ``sglang_http_async_engine.py:155-298``). v0 is a
synchronous batch engine with static shape buckets; the continuous-batching
scheduler and paged Pallas attention land on top of this API.

Shape discipline (XLA: trace once, reuse):
- prompts are LEFT-padded to a prompt-length bucket; batch padded to a batch
  bucket; decode runs a ``lax.while_loop`` with early exit when every row
  hit a stop token, writing tokens/logprobs into fixed [B, max_new] buffers.
- one compiled executable per (batch_bucket, prompt_bucket, max_new,
  sampling-params) tuple, cached on the engine.

Weight hot-swap: ``update_weights`` replaces the param pytree the compiled
fns close over — params are an ARGUMENT, so no recompilation (same shapes,
same shardings); the old buffers are freed by donation.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from polyrl_tpu import obs
from polyrl_tpu.models import decoder
from polyrl_tpu.rollout.sampling import SamplingParams, sample_token


def next_bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds largest bucket {buckets[-1]}")


def pack_left_padded(prompt_ids, pad_token_id: int, bb: int, pb: int):
    """Left-pad prompts into [bb, pb] (ids, mask) — shared by the fused and
    streaming decode paths so padding semantics can't drift."""
    ids = np.full((bb, pb), pad_token_id, np.int32)
    mask = np.zeros((bb, pb), np.float32)
    for i, p in enumerate(prompt_ids):
        ids[i, pb - len(p):] = np.asarray(p, np.int32)
        mask[i, pb - len(p):] = 1.0
    return ids, mask


@dataclasses.dataclass
class GenerationOutput:
    """Per-request result mirroring the fields the reference's manager
    consumes from SGLang's /generate (handlers.rs:215-251): token ids +
    per-token logprobs + finish reason + counts."""

    output_ids: np.ndarray          # [n_new] int32, truncated at stop
    output_token_logprobs: np.ndarray  # [n_new] f32
    finish_reason: str              # "stop" | "length" | "abort"
    prompt_tokens: int
    completion_tokens: int
    # which push version sampled each token (the wire protocol's per-token
    # weight_version, carried in-process too so colocated pipelined runs
    # feed the same staleness ledger / mixed-version TIS as remote ones)
    output_token_weight_versions: list | None = None


class RolloutEngine:
    """In-process rollout engine over one jax mesh (single-chip or sharded)."""

    def __init__(
        self,
        cfg: decoder.ModelConfig,
        params: Any,
        mesh=None,
        pad_token_id: int = 0,
        batch_buckets: tuple[int, ...] = (8, 16, 32, 64, 128, 256),
        prompt_buckets: tuple[int, ...] = (128, 256, 512, 1024, 2048, 4096),
        kv_cache_dtype=jnp.bfloat16,
    ):
        self.cfg = cfg
        self.params = params
        self.mesh = mesh
        self.pad_token_id = pad_token_id
        self.batch_buckets = batch_buckets
        self.prompt_buckets = prompt_buckets
        self.kv_cache_dtype = kv_cache_dtype
        self._compiled: dict = {}
        self.weight_version = 0
        self._released = False
        # serving stats mirroring the reference's queue-depth telemetry
        # (patches.py:423-425): running/queued counts + last throughput.
        self.num_running = 0
        self.num_queued = 0
        self.last_gen_throughput = 0.0

    # -- weight lifecycle (reference: /update_weights_from_agent,
    #    release/resume_memory_occupation) --------------------------------

    def update_weights(self, params: Any, version: int | None = None) -> None:
        import jax

        if (jax.tree_util.tree_structure(params)
                != jax.tree_util.tree_structure(self.params)):
            raise ValueError(
                "update_weights tree structure mismatch (quantized engines "
                "need the push re-quantized first — models/quant.py)")
        self.params = params
        self.weight_version = self.weight_version + 1 if version is None else version

    def release_memory(self) -> None:
        """Yield HBM to a colocated trainer (reference trainer_mode,
        stream_fsdp_workers.py:485-492). KV caches are per-call here, so
        v0 only flags the state; params stay (they're shared with the
        trainer in colocated mode)."""
        self._released = True

    def resume_memory(self) -> None:
        self._released = False

    # -- generate ---------------------------------------------------------

    def _build_generate(self, bb: int, pb: int, sp: SamplingParams):
        cfg = self.cfg
        max_total = pb + sp.max_new_tokens
        stop_ids = jnp.asarray(sp.stop_token_ids or (-1,), dtype=jnp.int32)

        def gen_fn(params, ids, mask, rng):
            # ids/mask: [bb, pb] left-padded
            positions = jnp.maximum(jnp.cumsum(mask, axis=-1) - 1, 0).astype(jnp.int32)
            cache = decoder.make_cache(cfg, bb, max_total, dtype=self.kv_cache_dtype)
            cache_mask = jnp.concatenate(
                [mask, jnp.zeros((bb, max_total - pb), mask.dtype)], axis=-1
            )
            last_logits, cache = decoder.forward(
                params, cfg, ids, positions, cache_mask, cache=cache, write_idx=0,
                logits_for=jnp.full((bb,), pb - 1, jnp.int32),
            )  # [bb, V] — left-padded prompts all end at pb-1

            out_tokens = jnp.full((bb, sp.max_new_tokens), self.pad_token_id, jnp.int32)
            out_logps = jnp.zeros((bb, sp.max_new_tokens), jnp.float32)
            prompt_len = jnp.sum(mask, axis=-1).astype(jnp.int32)  # [bb]
            # batch-bucket padding rows (empty prompts) start done, so the
            # early-exit fires as soon as every REAL row hit a stop token.
            done = prompt_len == 0

            def cond(state):
                step, done, *_ = state
                return (step < sp.max_new_tokens) & ~jnp.all(done)

            def body(state):
                step, done, last_logits, cache, cache_mask, out_tokens, out_logps, rng = state
                rng, sub = jax.random.split(rng)
                token, logp = sample_token(last_logits, sub, sp)
                token = jnp.where(done, self.pad_token_id, token)
                logp = jnp.where(done, 0.0, logp)
                out_tokens = jax.lax.dynamic_update_slice(out_tokens, token[:, None], (0, step))
                out_logps = jax.lax.dynamic_update_slice(out_logps, logp[:, None], (0, step))
                hit_stop = jnp.any(token[:, None] == stop_ids[None, :], axis=-1)
                new_done = done | hit_stop

                write_idx = pb + step
                cache_mask = cache_mask.at[:, pb + step].set(
                    jnp.where(done, 0.0, 1.0).astype(cache_mask.dtype)
                )
                pos = (prompt_len + step)[:, None]
                step_logits, cache = decoder.forward(
                    params, cfg, token[:, None], pos, cache_mask,
                    cache=cache, write_idx=write_idx,
                )
                return (step + 1, new_done, step_logits[:, 0, :], cache,
                        cache_mask, out_tokens, out_logps, rng)

            state = (0, done, last_logits, cache, cache_mask, out_tokens, out_logps, rng)
            state = jax.lax.while_loop(cond, body, state)
            _, done, _, _, _, out_tokens, out_logps, _ = state
            return out_tokens, out_logps, done

        return jax.jit(gen_fn, donate_argnums=())

    def generate(
        self,
        prompt_ids: list[list[int]] | list[np.ndarray],
        sampling: SamplingParams,
        rng: jax.Array | None = None,
    ) -> list[GenerationOutput]:
        """Batch-generate; returns one GenerationOutput per prompt."""
        t0 = time.monotonic()
        n = len(prompt_ids)
        self.num_running = n
        bb = next_bucket(n, self.batch_buckets)
        max_prompt = max(len(p) for p in prompt_ids)
        pb = next_bucket(max_prompt, self.prompt_buckets)

        ids, mask = pack_left_padded(prompt_ids, self.pad_token_id, bb, pb)

        key = (bb, pb, sampling)
        if key not in self._compiled:
            self._compiled[key] = self._build_generate(bb, pb, sampling)
        fn = self._compiled[key]
        rng = rng if rng is not None else jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        out_tokens, out_logps, done = jax.device_get(fn(self.params, ids, mask, rng))

        results = []
        stop_set = set(sampling.stop_token_ids)
        total_new = 0
        for i in range(n):
            toks = out_tokens[i]
            lps = out_logps[i]
            n_new = sampling.max_new_tokens
            finish = "length"
            for j, t in enumerate(toks):
                if int(t) in stop_set:
                    n_new = j + 1  # include the stop token (reference keeps eos)
                    finish = "stop"
                    break
            total_new += n_new
            results.append(
                GenerationOutput(
                    output_ids=toks[:n_new].copy(),
                    output_token_logprobs=lps[:n_new].copy(),
                    finish_reason=finish,
                    prompt_tokens=len(prompt_ids[i]),
                    completion_tokens=n_new,
                    # one jitted dispatch samples the whole batch, so every
                    # token shares the version installed at dispatch time
                    output_token_weight_versions=[self.weight_version] * n_new,
                )
            )
        dt = time.monotonic() - t0
        if dt > 0:
            # per-request decode rate distribution (one batch dispatch →
            # every request shares the wall clock; the spread comes from
            # early-stopping rows finishing with fewer tokens)
            for r in results:
                if r.completion_tokens:
                    obs.observe("rollout/decode_tok_s",
                                r.completion_tokens / dt)
        self.last_gen_throughput = total_new / dt if dt > 0 else 0.0
        self.num_running = 0
        return results
