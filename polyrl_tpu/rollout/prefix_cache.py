"""Page-granular prefix cache for the continuous-batching engine.

TPU-native analogue of SGLang's RadixAttention prefix cache (SURVEY.md §2.2
native-census row 1; flushed after weight updates, reference
patches.py:374-377): completed full pages of prompt KV are published under a
chained page-content hash; later admissions reuse the longest matched run of
pages and prefill only the suffix (``decoder.prefill_suffix_into_pages``).
Pages are shared read-only with refcounts; unreferenced entries stay
resident and are LRU-evicted back to the page allocator under pool
pressure. GRPO's n-samples-per-prompt makes the hit rate structural: the
first sample prefills, the other n−1 reuse every full prompt page.
"""

from __future__ import annotations

import dataclasses
from typing import Callable


@dataclasses.dataclass
class _Entry:
    key: tuple
    page: int
    refcount: int = 0
    tick: int = 0
    orphaned: bool = False  # dropped from the map while still referenced
    # collision guard: the hash key alone is NOT trusted (a 64-bit collision
    # would silently serve another prompt's KV). Each entry records its own
    # page's tokens and the identity of its parent entry; a match requires
    # token equality at every page AND that the parent chain is the exact
    # sequence of entries already verified for this request.
    page_toks: tuple = ()
    parent: "_Entry | None" = None
    # host-RAM spill tier (rollout/kvspill.py): a spilled entry's KV lives
    # in the HostSpillPool under spill_handle and ``page`` is STALE — the
    # engine restores it into a fresh physical page (updating ``page``)
    # before any attach. Only refcount==0 entries ever spill.
    spilled: bool = False
    spill_handle: int = -1


class PrefixCache:
    def __init__(self, page_size: int, free_pages: Callable[[list[int]], None]):
        self.page_size = page_size
        self._free_pages = free_pages
        self._map: dict[tuple, _Entry] = {}
        self._tick = 0
        self.hits = 0       # pages served from cache
        self.misses = 0     # full pages prefilled fresh
        # eviction cause split (ARCHITECTURE.md "KV memory plane"): the
        # spill tier needs to know WHICH kind of page it is stealing from —
        # capacity = pool-pressure LRU (+ stale-squatter replacement),
        # flush = weight swap / memory release invalidation (immediate
        # frees AND deferred orphan frees), preref_ttl = orphan frees
        # during a group pre-ref TTL sweep (``release(cause=...)``).
        self.evictions = {"capacity": 0, "flush": 0, "preref_ttl": 0}
        # cause of the most recent _free_pages call: the engine's ledger
        # wrapper reads it to attribute cache-side frees (set BEFORE the
        # callback runs)
        self.last_free_cause = "capacity"
        # request-level counters: the page-granular hits/misses above are
        # length-skewed (one 4k-prompt hit counts 64× a 128-token hit), so
        # the reported hit RATE said nothing about how many requests
        # actually skipped prefill work. The engine notes one hit/miss per
        # admitted request (any matched page = hit).
        self.req_hits = 0
        self.req_misses = 0
        # cold-first capacity eviction (set by the engine when the page
        # ledger is on): physical page id → idle age in dispatches.
        # Eviction then prefers the COLDEST unreferenced entries instead
        # of insertion order, so a hot shared group prefix is never evicted
        # while a cold singleton survives.
        self.idle_age: "Callable[[int], int] | None" = None
        self.evict_cold_first = 0  # pages evicted under cold-first order
        # spill-tier hook (set by the engine when the spill tier is on):
        # called with entries whose SPILLED content must be dropped (a
        # flush, or a stale-squatter replacement, while spilled) — their
        # physical page is already free, so they must NOT go through
        # _free_pages.
        self.drop_spilled: "Callable[[list], None] | None" = None

    def _free(self, pages: list[int], cause: str) -> None:
        """Single free choke point: book the cause, then hand the pages
        back through the engine's callback (which may feed the page
        ledger off ``last_free_cause``)."""
        self.evictions[cause] = self.evictions.get(cause, 0) + len(pages)
        self.last_free_cause = cause
        self._free_pages(pages)

    # -- keys ---------------------------------------------------------------

    def _keys_for(self, tokens: list[int], n_pages: int) -> list[tuple]:
        keys = []
        parent: tuple = ()
        for i in range(n_pages):
            page_toks = tuple(tokens[i * self.page_size:(i + 1) * self.page_size])
            parent = (hash((parent, page_toks)),)
            keys.append(parent)
        return keys

    # -- lookup / publish ----------------------------------------------------

    def match(self, tokens: list[int]) -> tuple[list[int], list[_Entry]]:
        """Longest run of cached full pages for this prompt, holding a ref on
        each. At least one token is always left for the suffix (the prefill
        must produce last-token logits)."""
        n_full = max(0, (len(tokens) - 1) // self.page_size)
        pages: list[int] = []
        entries: list[_Entry] = []
        self._tick += 1
        prev: _Entry | None = None
        for i, key in enumerate(self._keys_for(tokens, n_full)):
            e = self._map.get(key)
            page_toks = tuple(
                tokens[i * self.page_size:(i + 1) * self.page_size])
            if e is None or e.page_toks != page_toks or e.parent is not prev:
                break
            e.refcount += 1
            e.tick = self._tick
            pages.append(e.page)
            entries.append(e)
            prev = e
        self.hits += len(pages)
        return pages, entries

    def publish(self, tokens: list[int], page_ids: list[int],
                n_cached: int,
                matched_entries: "list[_Entry] | None" = None
                ) -> list[tuple[int, _Entry]]:
        """Register the freshly prefilled full pages ``page_ids[n_cached:]``
        (ownership moves to the cache; caller keeps a ref). Returns
        ``(prompt_page_index, entry)`` for each page actually published —
        pages whose key already exists stay caller-owned.

        ``matched_entries`` is the entry list the caller got from
        ``match()`` — the chain the request was actually verified against.
        Resolving the parent by key alone could chain children to a
        REPLACED or colliding entry under that key, making them silently
        unreachable (parent-identity check fails on every later match)."""
        n_full = max(0, (len(tokens) - 1) // self.page_size)
        keys = self._keys_for(tokens, n_full)
        out: list[tuple[int, _Entry]] = []
        self._tick += 1
        if n_cached > 0:
            # resolving by key instead would chain children to whatever entry
            # NOW sits under that key — possibly a replaced/colliding one
            assert matched_entries and len(matched_entries) >= n_cached, \
                "publish with n_cached > 0 requires the match() entry list"
            prev: _Entry | None = matched_entries[n_cached - 1]
        else:
            prev = None
        for i in range(n_cached, n_full):
            key = keys[i]
            page_toks = tuple(
                tokens[i * self.page_size:(i + 1) * self.page_size])
            existing = self._map.get(key)
            if existing is not None:
                # duplicate key: caller's page stays slot-private. Only keep
                # chaining if the existing entry REALLY is this prefix
                # (token + parent-identity check — a colliding entry would
                # poison every child published under it)
                if existing.page_toks == page_toks and existing.parent is prev:
                    prev = existing
                    continue
                if existing.refcount == 0:
                    # stale squatter (e.g. a child whose parent was evicted,
                    # or a colliding entry): replace it so this prefix stays
                    # cacheable instead of permanently re-prefilling
                    del self._map[key]
                    if existing.spilled:
                        # its physical page is already free — only the
                        # host-side copy dies
                        if self.drop_spilled is not None:
                            self.drop_spilled([existing])
                    else:
                        self._free([existing.page], "capacity")
                    e = _Entry(key=key, page=page_ids[i], refcount=1,
                               tick=self._tick, page_toks=page_toks,
                               parent=prev)
                    self._map[key] = e
                    out.append((i, e))
                    prev = e
                    continue
                break
            e = _Entry(key=key, page=page_ids[i], refcount=1, tick=self._tick,
                       page_toks=page_toks, parent=prev)
            self._map[key] = e
            out.append((i, e))
            prev = e
        self.misses += max(0, n_full - n_cached)
        return out

    def note_request(self, hit: bool) -> None:
        """One admitted request's cache outcome (request-granular — the
        page counters in ``match``/``publish`` stay as they are)."""
        if hit:
            self.req_hits += 1
        else:
            self.req_misses += 1

    # -- refs ----------------------------------------------------------------

    def retain(self, entries: list[_Entry], n: int = 1) -> None:
        """Take ``n`` extra refs on each entry (group-shared prefill
        pre-refs: a leader's publish pre-takes group_size−1 refs so
        pool-pressure eviction cannot race its siblings' attach; each ref
        is dropped via ``release`` as a sibling attaches or the group's
        pre-refs are swept/disbanded)."""
        if n <= 0:
            return
        for e in entries:
            e.refcount += n

    def release(self, entries: list[_Entry], cause: str = "flush") -> None:
        """Drop one ref per entry; orphaned entries (flushed while
        referenced) free their page at refcount 0. Orphans only exist
        post-flush, so their frees default to the ``flush`` cause; the
        engine's pre-ref TTL sweep overrides with ``preref_ttl``."""
        freed: list[int] = []
        for e in entries:
            e.refcount -= 1
            if e.refcount == 0 and e.orphaned:
                freed.append(e.page)
        if freed:
            self._free(freed, cause)

    # -- eviction / flush ----------------------------------------------------

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` unreferenced HBM-resident pages. With the
        ledger's ``idle_age`` hook attached, the COLDEST pages go first
        (idle-age descending, insertion tick as the tiebreak) — a hot
        shared group prefix is never evicted while a cold singleton
        survives; without it, plain LRU by insertion tick. Spilled entries
        are skipped: their physical page is already free, so evicting them
        would reclaim no HBM. Returns how many pages were freed."""
        candidates = [e for e in self._map.values()
                      if e.refcount == 0 and not e.spilled]
        if self.idle_age is not None:
            age = self.idle_age
            victims = sorted(candidates,
                             key=lambda e: (-age(e.page), e.tick))[:n_pages]
            self.evict_cold_first += len(victims)
        else:
            victims = sorted(candidates, key=lambda e: e.tick)[:n_pages]
        if not victims:
            return 0
        for e in victims:
            del self._map[e.key]
        self._free([e.page for e in victims], "capacity")
        return len(victims)

    def spill_candidates(self) -> list[_Entry]:
        """Entries the spill tier may page out: unreferenced, HBM-resident
        (the sweep ranks them by ledger idle age and takes the coldest)."""
        return [e for e in self._map.values()
                if e.refcount == 0 and not e.spilled]

    def flush(self) -> None:
        """Invalidate everything (weight update / memory release):
        unreferenced pages return to the allocator now; referenced ones are
        orphaned and freed when their last holder releases; spilled entries
        drop their host-side copy (their physical page is already free —
        abort/flush-while-spilled frees both tiers)."""
        freed: list[int] = []
        spilled: list[_Entry] = []
        for e in self._map.values():
            if e.spilled:
                spilled.append(e)
            elif e.refcount == 0:
                freed.append(e.page)
            else:
                e.orphaned = True
        self._map.clear()
        if spilled and self.drop_spilled is not None:
            self.drop_spilled(spilled)
        if freed:
            self._free(freed, "flush")

    @property
    def num_entries(self) -> int:
        return len(self._map)

    @property
    def request_hit_frac(self) -> float:
        """Request-level hit fraction (length-unbiased, unlike hit_rate)."""
        total = self.req_hits + self.req_misses
        return self.req_hits / total if total else 0.0

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {"prefix_cache/entries": float(len(self._map)),
                "prefix_cache/hit_pages": float(self.hits),
                "prefix_cache/hit_rate": self.hits / total if total else 0.0,
                "prefix_cache/req_hits": float(self.req_hits),
                "prefix_cache/req_misses": float(self.req_misses),
                "prefix_cache/req_hit_frac": self.request_hit_frac,
                # eviction cause split — one undifferentiated total told
                # the spill tier nothing about what it would be stealing
                "prefix_cache/evict_capacity": float(
                    self.evictions["capacity"]),
                # capacity evictions ordered cold-first by ledger idle age
                # (0 when the ledger hook is off — insertion-order LRU)
                "prefix_cache/evict_cold_first": float(
                    self.evict_cold_first),
                "prefix_cache/evict_flush": float(self.evictions["flush"]),
                "prefix_cache/evict_preref_ttl": float(
                    self.evictions["preref_ttl"])}
