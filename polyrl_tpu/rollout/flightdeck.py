"""Engine flight deck: per-request lifecycle + scheduler occupancy ledger
(ARCHITECTURE.md "Engine flight deck").

The rollout engine was the last black box on the serving plane: the
trainer had goodput attribution and a health plane (PR 5), but slot
occupancy, page-pool pressure, admission queue wait, and server-side
TTFT/TPOT were measured nowhere — ``server_info`` exposed two queue
counts and one instantaneous throughput scalar, and bench measured TTFT
from the client only. DualKV (PAPERS.md) frames exactly these signals
(shared-prefix hit rate, KV page residency) as the levers at GRPO's
n-samples-per-prompt traffic shape, and the Adaptive Placement scheduler
needs per-engine load richer than ``num_running_reqs`` to place work.

Two ledgers, one invariant:

- **Request ledger** — every admitted request's queue wait (submit →
  admission dispatch), prefill wall (admission → first token), TTFT
  (submit → first token), mean decode interval (TPOT), and prefill vs
  decode token counts. Distributions land in engine-local log2
  histograms (``Histogram`` — served by ``server_info``/``/statusz``
  without a trainer attached) AND the process-global registry
  (``engine/ttft_s``, ``engine/tpot_s``, ``engine/queue_wait_s``,
  ``engine/prefill_s``) so a colocated engine's tails ride the trainer's
  step records like every other distribution.
- **Scheduler step ledger** — per-decode-dispatch occupancy (active
  slots / max_slots, pad fraction), page-allocator utilization +
  prefix-cache residency, run-ahead depth (dispatch outputs in flight),
  and admission wave sizes.

The two sides double-count nothing and must RECONCILE: scheduler-side
token totals (counted at admission dispatch and at emission) equal the
per-request totals folded in at finalize, exactly, whenever the engine is
quiescent — ``attributed_frac`` is the live ratio, the serving-plane
analogue of the PR 5 goodput ledger's ``goodput/attributed_frac``. A
leaked slot, a skipped finalize, or an emission past a dead slot breaks
the equality (pinned by test).

All mutation happens on the engine loop thread; ``snapshot()`` readers
(HTTP handler threads serving ``server_info``/``/statusz``) take the same
lock, so a snapshot is internally consistent.
"""

from __future__ import annotations

import math
import threading
import time

from polyrl_tpu.obs.histogram import Histogram, observe


class ThroughputEWMA:
    """Time-aware EWMA over throughput samples.

    ``last_gen_throughput`` used to be the raw rate of the most recent
    drain window — one fast burst (a pipeline stall flushing) or one slow
    tick aliased every heartbeat-sampled consumer (the manager's stats
    poller, /statusz, the bench peak sampler). The EWMA weight adapts to
    the gap between samples (``alpha = 1 - exp(-dt/tau)``), so irregular
    emission bursts are smoothed over ``tau`` seconds of wall time rather
    than a fixed sample count."""

    def __init__(self, tau_s: float = 5.0):
        self.tau_s = float(tau_s)
        self.value = 0.0
        self._t_last: float | None = None

    def update(self, rate: float, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        if self._t_last is None:
            self.value = float(rate)
        else:
            dt = max(0.0, now - self._t_last)
            alpha = 1.0 - math.exp(-dt / self.tau_s) if self.tau_s > 0 else 1.0
            self.value += alpha * (float(rate) - self.value)
        self._t_last = now
        return self.value

    def reset(self) -> None:
        self.value = 0.0
        self._t_last = None


class _ReqRecord:
    """Lifecycle of one admitted request (slot-resident)."""

    __slots__ = ("rid", "t_submit", "t_admit", "t_first", "t_last",
                 "prefill_tokens", "cached_tokens", "decode_tokens",
                 "salvaged")

    def __init__(self, rid: str, t_submit: float, t_admit: float,
                 prefill_tokens: int, cached_tokens: int):
        self.rid = rid
        self.t_submit = t_submit
        self.t_admit = t_admit
        self.t_first = 0.0
        self.t_last = 0.0
        self.prefill_tokens = prefill_tokens
        self.cached_tokens = cached_tokens
        self.decode_tokens = 0
        self.salvaged = False


class EngineFlightDeck:
    """Both ledgers + the reconciliation invariant for one CBEngine."""

    # EWMA weight for the per-dispatch occupancy signal exported to the
    # manager's placement view (dispatches are sub-second; ~0.05 smooths
    # over a few dozen dispatches without hiding a real collapse)
    OCC_ALPHA = 0.05

    def __init__(self, max_slots: int, num_pages: int, page_size: int):
        self.max_slots = max(1, int(max_slots))
        # page 0 is the reserved null page — it can never be allocated
        self.num_alloc_pages = max(1, int(num_pages) - 1)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        self._recs: list[_ReqRecord | None] = [None] * self.max_slots

        # request-side cumulative totals (folded at finalize)
        self.req_prefill_tokens = 0
        self.req_decode_tokens = 0
        self.requests_finished = 0
        self.requests_salvaged = 0
        # scheduler-side cumulative totals (counted at dispatch/emission)
        self.sched_prefill_tokens = 0
        self.sched_decode_tokens = 0
        # prompt tokens served from cached/group-shared pages instead of
        # being recomputed (group-shared prefill headline signal):
        # prefill_reuse_frac = cached / sched_prefill. Counted at admission
        # like sched_prefill_tokens — reuse is a scheduler-side property.
        self.cached_prompt_tokens = 0

        # KV-read ledger (shared-prefix decode attention): pages the decode
        # kernels actually STREAM from HBM vs pages LOGICALLY attended —
        # each decode group's shared prefix streams once per group instead
        # of once per sibling, and this ledger is what quantifies the
        # bandwidth actually deduplicated (engine/kv_read_pages_per_token,
        # engine/shared_prefix_read_frac). Counts are dispatch-time
        # estimates from the host mirrors (each fused step can cross at
        # most one page boundary past the sample).
        self.kv_pages_streamed = 0
        self.kv_pages_logical = 0
        self.kv_read_tokens = 0

        # scheduler step ledger (updated per decode dispatch / admission)
        self.decode_dispatches = 0
        self.idle_iters = 0
        self.admit_waves = 0
        self.admitted_requests = 0
        self.occupancy_last = 0.0
        self.occupancy_ewma = 0.0
        self.pad_frac_last = 0.0
        self.page_util_last = 0.0
        self.page_util_peak = 0.0
        self.cache_pages_last = 0
        self.run_ahead_last = 0
        self.queued_last = 0

        # engine-local distributions (cumulative — a standalone rollout
        # server has no trainer draining the global registry)
        self.hists: dict[str, Histogram] = {
            "ttft_s": Histogram(),
            "tpot_s": Histogram(),
            "queue_wait_s": Histogram(),
            "prefill_s": Histogram(),
            "occupancy": Histogram(),
            "page_util": Histogram(),
            "admit_batch": Histogram(),
        }

    # -- request lifecycle (loop thread) ------------------------------------

    def on_admit(self, slot: int, rid: str, t_submit: float,
                 prompt_tokens: int, cached_tokens: int = 0) -> None:
        """Admission dispatch for ``slot``: queue wait ends here; the
        request's prompt joins the scheduler-side prefill total."""
        now = time.monotonic()
        qw = max(0.0, now - t_submit)
        with self._lock:
            self._recs[slot] = _ReqRecord(rid, t_submit, now,
                                          int(prompt_tokens),
                                          int(cached_tokens))
            self.sched_prefill_tokens += int(prompt_tokens)
            self.cached_prompt_tokens += int(cached_tokens)
            self.admitted_requests += 1
            self.hists["queue_wait_s"].observe(qw)
        observe("engine/queue_wait_s", qw)

    def on_admit_wave(self, n: int) -> None:
        with self._lock:
            self.admit_waves += 1
            self.hists["admit_batch"].observe(float(n))

    def on_first_token(self, slot: int) -> None:
        now = time.monotonic()
        with self._lock:
            rec = self._recs[slot]
            if rec is None or rec.t_first:
                return
            rec.t_first = rec.t_last = now
            rec.decode_tokens += 1
            ttft = max(0.0, now - rec.t_submit)
            prefill = max(0.0, now - rec.t_admit)
            self.hists["ttft_s"].observe(ttft)
            self.hists["prefill_s"].observe(prefill)
        observe("engine/ttft_s", ttft)
        observe("engine/prefill_s", prefill)

    def on_decode(self, slot: int, n: int = 1) -> None:
        with self._lock:
            rec = self._recs[slot]
            if rec is None:
                return
            rec.decode_tokens += int(n)
            rec.t_last = time.monotonic()

    def on_emitted(self, n: int) -> None:
        """Scheduler-side emission total (the ``_count_tokens`` seam —
        counted independently of the per-slot records above so the
        reconciliation actually checks something)."""
        with self._lock:
            self.sched_decode_tokens += int(n)

    def on_salvage(self, slot: int) -> None:
        with self._lock:
            rec = self._recs[slot]
            if rec is not None:
                rec.salvaged = True

    def on_finalize(self, slot: int) -> None:
        """Fold the slot's record into the request-side totals; observe its
        mean decode interval (TPOT). Idempotent — a double finalize (abort
        racing a stop-token finish) folds once."""
        with self._lock:
            rec = self._recs[slot]
            if rec is None:
                return
            self._recs[slot] = None
            self.req_prefill_tokens += rec.prefill_tokens
            self.req_decode_tokens += rec.decode_tokens
            self.requests_finished += 1
            if rec.salvaged:
                self.requests_salvaged += 1
            tpot = None
            if rec.decode_tokens > 1 and rec.t_last > rec.t_first:
                tpot = (rec.t_last - rec.t_first) / (rec.decode_tokens - 1)
                self.hists["tpot_s"].observe(tpot)
        if tpot is not None:
            observe("engine/tpot_s", tpot)

    # -- scheduler step ledger (loop thread) --------------------------------

    def on_dispatch(self, active: int, free_pages: int, cache_pages: int,
                    run_ahead: int, queued: int) -> None:
        """One decode dispatch: sample occupancy + page pressure."""
        occ = min(1.0, active / self.max_slots)
        util = min(1.0, 1.0 - free_pages / self.num_alloc_pages)
        with self._lock:
            self.decode_dispatches += 1
            self.occupancy_last = occ
            if self.decode_dispatches == 1:  # seed, don't ramp from zero
                self.occupancy_ewma = occ
            else:
                self.occupancy_ewma += self.OCC_ALPHA * (occ
                                                         - self.occupancy_ewma)
            self.pad_frac_last = 1.0 - occ
            self.page_util_last = util
            self.page_util_peak = max(self.page_util_peak, util)
            self.cache_pages_last = int(cache_pages)
            self.run_ahead_last = int(run_ahead)
            self.queued_last = int(queued)
            self.hists["occupancy"].observe(occ)
            self.hists["page_util"].observe(util)

    def on_kv_read(self, streamed_pages: int, logical_pages: int,
                   tokens: int) -> None:
        """One decode dispatch's KV-read sample (``_account_kv_reads``)."""
        with self._lock:
            self.kv_pages_streamed += int(streamed_pages)
            self.kv_pages_logical += int(logical_pages)
            self.kv_read_tokens += int(tokens)

    def on_idle(self) -> None:
        with self._lock:
            self.idle_iters += 1

    # -- export --------------------------------------------------------------

    def attributed_frac(self) -> float:
        """Request-attributed tokens / scheduler-observed tokens. Exactly
        1.0 at quiescence; < 1.0 while requests are in flight; anything
        > 1.0 is a double-count bug."""
        sched = self.sched_prefill_tokens + self.sched_decode_tokens
        if sched == 0:
            return 1.0
        return (self.req_prefill_tokens + self.req_decode_tokens) / sched

    def prefill_reuse_frac(self) -> float:
        """Fraction of admitted prompt tokens whose KV came from the prefix
        cache / a group-shared leader instead of being recomputed — the
        group-shared-prefill headline. 0.0 before any admission."""
        if self.sched_prefill_tokens == 0:
            return 0.0
        return self.cached_prompt_tokens / self.sched_prefill_tokens

    def kv_read_pages_per_token(self) -> float:
        """KV pages streamed from HBM per decoded token — the bandwidth
        cost the shared-prefix decode kernel attacks. 0.0 before any
        decode dispatch."""
        if self.kv_read_tokens == 0:
            return 0.0
        return self.kv_pages_streamed / self.kv_read_tokens

    def shared_prefix_read_frac(self) -> float:
        """Fraction of logically-attended KV pages the decode kernels did
        NOT re-stream (deduplicated by the grouped prefix phase). 0.0 with
        sharing off or no group traffic; → (G−1)/G · prefix share of the
        sequence on a pure G-sibling workload."""
        if self.kv_pages_logical == 0:
            return 0.0
        return 1.0 - self.kv_pages_streamed / self.kv_pages_logical

    def server_info_fields(self) -> dict:
        """Flat keys merged into ``server_info`` — what the C++ manager's
        stats poller forwards and bench reads. Names stay flat (no ``/``)
        so the C++ json parser indexes them directly."""
        with self._lock:
            t = self.hists["ttft_s"]
            p = self.hists["tpot_s"]
            q = self.hists["queue_wait_s"]
            occ_mean = self.hists["occupancy"].mean
            out = {
                "occupancy": round(self.occupancy_ewma, 4),
                "occupancy_mean": round(occ_mean, 4),
                "page_util": round(self.page_util_last, 4),
                "page_util_peak": round(self.page_util_peak, 4),
                "run_ahead": self.run_ahead_last,
                "ttft_p50_s": round(t.percentile(50.0), 6),
                "ttft_p95_s": round(t.percentile(95.0), 6),
                "tpot_p50_s": round(p.percentile(50.0), 6),
                "tpot_p95_s": round(p.percentile(95.0), 6),
                "queue_wait_p95_s": round(q.percentile(95.0), 6),
                "attributed_frac": round(self.attributed_frac(), 6),
                "prefill_reuse_frac": round(self.prefill_reuse_frac(), 6),
                "kv_read_pages_per_token": round(
                    self.kv_read_pages_per_token(), 4),
                "shared_prefix_read_frac": round(
                    self.shared_prefix_read_frac(), 6),
            }
        return out

    def snapshot(self, active: int = 0, queued: int = 0) -> dict:
        """The ``/statusz`` ``engine`` section (nested, human-first)."""
        with self._lock:
            hists = {name: {
                "p50": h.percentile(50.0), "p95": h.percentile(95.0),
                "p99": h.percentile(99.0),
                "max": h.vmax if h.count else 0.0,
                "mean": h.mean, "count": float(h.count),
            } for name, h in self.hists.items() if h.count}
            return {
                "requests": {
                    "active": int(active),
                    "queued": int(queued),
                    "finished": self.requests_finished,
                    "salvaged": self.requests_salvaged,
                    "admitted": self.admitted_requests,
                },
                "tokens": {
                    "req_prefill": self.req_prefill_tokens,
                    "req_decode": self.req_decode_tokens,
                    "sched_prefill": self.sched_prefill_tokens,
                    "sched_decode": self.sched_decode_tokens,
                    "cached_prompt": self.cached_prompt_tokens,
                    "attributed_frac": round(self.attributed_frac(), 6),
                    "prefill_reuse_frac": round(self.prefill_reuse_frac(), 6),
                },
                "occupancy": {
                    "last": round(self.occupancy_last, 4),
                    "ewma": round(self.occupancy_ewma, 4),
                    "pad_frac": round(self.pad_frac_last, 4),
                    "max_slots": self.max_slots,
                },
                "pages": {
                    "util": round(self.page_util_last, 4),
                    "peak_util": round(self.page_util_peak, 4),
                    "cache_pages": self.cache_pages_last,
                    "total": self.num_alloc_pages,
                    # shared-prefix decode attention: HBM reads vs logical
                    "kv_streamed": self.kv_pages_streamed,
                    "kv_logical": self.kv_pages_logical,
                    "kv_read_pages_per_token": round(
                        self.kv_read_pages_per_token(), 4),
                    "shared_prefix_read_frac": round(
                        self.shared_prefix_read_frac(), 6),
                },
                "dispatch": {
                    "decode_dispatches": self.decode_dispatches,
                    "run_ahead": self.run_ahead_last,
                    "idle_iters": self.idle_iters,
                    "admit_waves": self.admit_waves,
                },
                "latency": hists,
            }
