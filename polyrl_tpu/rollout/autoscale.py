"""Closed-loop autoscaling: act on the trend rail (ARCHITECTURE.md
"Closed-loop autoscaling & degradation tiers").

The paper's adaptivity story (progressive workload balance offloading
rollout onto harvested spot capacity) had every INPUT built — the
elastic pool lifecycle (rollout/pool.py), the progressive balance
estimator's trend slopes (``BalanceEstimator.trends()``), the per-step
critical-path bottleneck attribution (``critpath/bottleneck``) and the
fleet ``engine/*`` gauges — but nothing ever ACTED on them. This module
closes the loop:

- :class:`AutoscaleController` — a deterministic policy ticked once per
  finished step from the trainer's fit loop. It consumes the trend rail
  (occupancy/bubble slopes, gated on ``balance_trends_valid``), the
  critical-path bottleneck segment and the fleet pool counters, and
  issues PoolManager actions: **request-add** (an endpoint acquired from
  a pluggable :class:`CapacityProvider` — e.g. the spot-market harness,
  rollout/spotmarket.py) and **proactive drain** of the least-loaded
  engine. Decisions run under hysteresis (``hold_steps`` consecutive
  ticks before a trend acts), per-action cooldowns, a min/max fleet
  envelope (envelope repair bypasses the trend gate — a pool below
  ``min_engines`` adds immediately) and a sliding-window rate limiter.
  Actions execute on a background worker thread (a drain sleeps out its
  grace window; the trainer loop must never stall on it) with at most
  one action in flight.
- **Degradation tiers** — when the fleet collapses the trainer degrades
  explicitly instead of stalling: tier 0 ``remote`` (>=1 active remote
  engine), tier 1 ``colocated`` (only the local time-sliced engine
  left), tier 2 ``local`` (no active engines, or a ``finish_locally``
  degraded completion just happened). The tier is the
  ``autoscale/degrade_tier`` step gauge (FlightRecorder watches it
  "high") and :meth:`hold_admission` is the pipeline's admission
  backpressure: new streams hold while ``active == 0``, releasing at
  ``admission_max_wait_s`` so the ``finish_locally`` path can
  degrade-complete rather than deadlock.

Every decision — acted, intended (dry run) or suppressed — lands as
structured ``autoscale/*`` step gauges plus the /statusz ``autoscale``
section (action, reason, inputs, suppressions), so the loop is
debuggable from one curl. Default OFF (``rollout.autoscale.enabled``):
a run without the controller is bitwise-identical to one predating it.

Scheduling reference: the Adaptive Placement framework and MindSpeed
RL's dynamic-resource thesis (PAPERS.md).
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from collections import deque

log = logging.getLogger(__name__)

# degradation ladder (the /statusz tier table): the trainer's serving
# posture, derived from pool membership every tick
TIERS = ("remote", "colocated", "local")

# decision vocabulary: the autoscale/action step gauge is an index here
ACTIONS = ("none", "add", "drain")

# why the controller decided what it decided (autoscale/reason indexes
# this tuple). The reason is recorded even when the action was then
# suppressed — "what it wanted and why it didn't" is the debug surface.
REASONS = ("none", "below_min", "above_max", "saturating", "underloaded")

# rollout-bound critical-path segments (obs/critical_path.py SEGMENTS
# indices: generate=0, bubble=4): a step bottlenecked there is starving
# on rollout capacity — an add signal alongside the trend slopes
_ROLLOUT_BOUND_SEGMENTS = (0.0, 4.0)

# rate-limiter window: max_actions_per_hour counts actions inside this
_RATE_WINDOW_S = 3600.0


@dataclasses.dataclass
class AutoscaleConfig:
    """``rollout.autoscale.*`` knobs (config.py RolloutSection).

    Default OFF everywhere: the controller is only constructed when
    ``enabled`` is true, so the default fit path is untouched."""
    enabled: bool = False
    # record intents (autoscale/intents_total + the statusz section)
    # without ever issuing a pool action
    dry_run: bool = False
    # fleet envelope, in ACTIVE engines: below min is repaired by an
    # immediate add (bypassing trend hysteresis, not the cooldown/rate
    # limiter), above max by a proactive drain
    min_engines: int = 1
    max_engines: int = 4
    # trend hysteresis: add when fleet-mean occupancy is at/above the
    # high water AND the trainer-bubble slope is rising past
    # bubble_slope_add (or the critical path is rollout-bound); drain
    # when occupancy is at/below the low water with a non-rising bubble.
    # Either condition must hold for hold_steps CONSECUTIVE ticks.
    occupancy_high: float = 0.75
    occupancy_low: float = 0.30
    bubble_slope_add: float = 0.0
    hold_steps: int = 2
    # per-action cooldowns: a join needs the bootstrap push + gate to
    # settle before its effect is measurable; drains are rarer still
    cooldown_add_s: float = 30.0
    cooldown_drain_s: float = 60.0
    # sliding-window rate limiter over BOTH action kinds (flap guard)
    max_actions_per_hour: int = 12
    # admission backpressure (trainer/pipeline.py gate): how long a new
    # stream may hold while the pool has ZERO active engines. Always
    # releases at the deadline — finish_locally degrades the batch
    # instead of the gate deadlocking the run. 0 disables the gate.
    admission_max_wait_s: float = 30.0


class CapacityProvider:
    """Where scale-up capacity comes from. The controller never creates
    engines itself — it asks the provider for one ready endpoint per
    add decision. rollout/spotmarket.py implements this over a scripted
    offer trace; a production provider would front a VM/TPU allocator."""

    def acquire(self) -> str | None:
        """Pop one ready-to-join endpoint, or None if the market has
        nothing on offer right now (the add is then suppressed as
        ``no_capacity`` and retried on a later tick)."""
        raise NotImplementedError

    def on_step(self, step: int) -> int:
        """Optional step-paced event hook (the spot market's ``step``
        time base); returns the number of events fired so the caller
        knows to refresh its fleet view mid-tick."""
        return 0


class AutoscaleController:
    """The policy loop. Construct with the fleet control plane
    (:class:`PoolManager`), the trend source (:class:`BalanceEstimator`)
    and optionally a :class:`CapacityProvider` + the
    :class:`RemoteRollout` (for the ``finish_locally`` degrade signal);
    call :meth:`tick` once per finished trainer step and merge the
    returned ``autoscale/*`` gauges into the step record."""

    def __init__(self, pool, balance, cfg: AutoscaleConfig | None = None,
                 capacity: CapacityProvider | None = None, rollout=None,
                 clock=time.monotonic):
        self.pool = pool
        self.balance = balance
        self.cfg = cfg or AutoscaleConfig(enabled=True)
        self.capacity = capacity
        self.rollout = rollout
        self._clock = clock
        self._lock = threading.Lock()
        self._closed = threading.Event()
        # single-worker action executor: a drain blocks on its grace
        # window — off the trainer thread, one action in flight at most
        self._q: queue.Queue = queue.Queue()
        self._worker: threading.Thread | None = None
        self._inflight = threading.Event()
        # cumulative totals (step-record gauges)
        self.ticks = 0
        self.adds_total = 0
        self.drains_total = 0
        self.intents_total = 0
        self.suppressed_total = 0
        self.exec_failures = 0
        self.gate_wait_s_total = 0.0
        self.degrade_tier = 0
        # hysteresis + cooldown + rate-limit state
        self._hold_add = 0
        self._hold_drain = 0
        self._last_add_t = float("-inf")
        self._last_drain_t = float("-inf")
        self._action_times: deque[float] = deque()
        self._last_fallbacks = 0
        # last decision, for the /statusz autoscale section
        self._last: dict = {"step": -1, "action": "none", "reason": "none",
                            "inputs": {}, "suppressions": []}

    def close(self) -> None:
        self._closed.set()
        if self._worker is not None:
            self._q.put(None)
            self._worker.join(timeout=10.0)
            self._worker = None

    # -- the policy tick ---------------------------------------------------

    def tick(self, step: int, *, fleet: dict | None = None,
             record: dict | None = None) -> dict[str, float]:
        """One policy evaluation. ``fleet`` is the just-fetched
        ``PoolManager.counters()`` dict (the trainer passes it in so the
        tick never re-sweeps what the step already swept); ``record``
        the previous step's record (for ``critpath/bottleneck``)."""
        cfg = self.cfg
        with self._lock:
            self.ticks += 1
        if self.capacity is not None and self.capacity.on_step(step):
            # step-paced market events just changed membership: refresh
            # so the decision sees the post-event fleet, not a stale one
            fleet = None
        if fleet is None:
            fleet = self.pool.counters()
        trends = self.balance.trends() if self.balance is not None else {}
        record = record or {}

        active = float(fleet.get("pool/active", 0.0))
        occupancy = float(fleet.get("engine/occupancy", 0.0))
        trends_valid = bool(trends.get("balance_trends_valid", 0.0))
        bubble_slope = float(trends.get("bubble_slope", 0.0))
        bottleneck = float(record.get("critpath/bottleneck", -1.0))
        tier = self._compute_tier()
        inputs = {
            "active": active,
            "occupancy": occupancy,
            "occupancy_slope": float(trends.get("occupancy_slope", 0.0)),
            "bubble_slope": bubble_slope,
            "bottleneck": bottleneck,
            "trends_valid": trends_valid,
        }

        suppressions: list[str] = []
        if not cfg.enabled:
            want, reason = "none", "none"
            suppressions.append("disabled")
        else:
            want, reason = self._decide(active, occupancy, bubble_slope,
                                        bottleneck, trends_valid,
                                        suppressions)
        acted = "none"
        if want != "none":
            acted = self._issue(want, suppressions)

        with self._lock:
            self.suppressed_total += len(suppressions)
            self.degrade_tier = tier
            self._last = {"step": int(step), "action": acted,
                          "reason": reason, "inputs": inputs,
                          "suppressions": list(suppressions)}
            return {
                "autoscale/enabled": 1.0 if cfg.enabled else 0.0,
                "autoscale/dry_run": 1.0 if cfg.dry_run else 0.0,
                "autoscale/ticks": float(self.ticks),
                "autoscale/action": float(ACTIONS.index(acted)),
                "autoscale/reason": float(REASONS.index(reason)),
                "autoscale/adds_total": float(self.adds_total),
                "autoscale/drains_total": float(self.drains_total),
                "autoscale/intents_total": float(self.intents_total),
                "autoscale/suppressed_total": float(self.suppressed_total),
                "autoscale/exec_failures": float(self.exec_failures),
                "autoscale/degrade_tier": float(tier),
                "autoscale/trends_valid": 1.0 if trends_valid else 0.0,
                "autoscale/admission_gate_wait_s": float(
                    self.gate_wait_s_total),
            }

    def _decide(self, active: float, occupancy: float, bubble_slope: float,
                bottleneck: float, trends_valid: bool,
                suppressions: list[str]) -> tuple[str, str]:
        """Envelope repair first (structural, bypasses trend hysteresis),
        then the trend policy gated on a valid estimator window."""
        cfg = self.cfg
        if active < cfg.min_engines:
            self._hold_add = self._hold_drain = 0
            return "add", "below_min"
        if active > cfg.max_engines:
            self._hold_add = self._hold_drain = 0
            return "drain", "above_max"
        if not trends_valid:
            # cold estimator window: 1-2 point slopes are noise, not a
            # reason to move capacity (BalanceEstimator cold-window guard)
            suppressions.append("trends_invalid")
            self._hold_add = self._hold_drain = 0
            return "none", "none"
        rollout_bound = bottleneck in _ROLLOUT_BOUND_SEGMENTS
        want_add = (occupancy >= cfg.occupancy_high
                    and (bubble_slope > cfg.bubble_slope_add
                         or rollout_bound)
                    and active < cfg.max_engines)
        want_drain = (occupancy <= cfg.occupancy_low
                      and bubble_slope <= 0.0 and not rollout_bound
                      and active > cfg.min_engines)
        self._hold_add = self._hold_add + 1 if want_add else 0
        self._hold_drain = self._hold_drain + 1 if want_drain else 0
        if want_add and self._hold_add >= cfg.hold_steps:
            return "add", "saturating"
        if want_drain and self._hold_drain >= cfg.hold_steps:
            return "drain", "underloaded"
        if want_add or want_drain:
            suppressions.append("hold")
        return "none", "none"

    def _issue(self, kind: str, suppressions: list[str]) -> str:
        """Run a wanted action through the suppression gauntlet
        (in-flight / cooldown / rate limit / capacity / dry run) and, if
        it survives, hand it to the worker. Returns the action actually
        taken (``none`` when suppressed)."""
        cfg = self.cfg
        now = self._clock()
        if self._inflight.is_set():
            suppressions.append("action_in_flight")
            return "none"
        if kind == "add" and now - self._last_add_t < cfg.cooldown_add_s:
            suppressions.append("cooldown_add")
            return "none"
        if kind == "drain" and now - self._last_drain_t < cfg.cooldown_drain_s:
            suppressions.append("cooldown_drain")
            return "none"
        while self._action_times and now - self._action_times[0] > _RATE_WINDOW_S:
            self._action_times.popleft()
        if len(self._action_times) >= cfg.max_actions_per_hour:
            suppressions.append("rate_limited")
            return "none"
        if kind == "add":
            endpoint = self.capacity.acquire() \
                if self.capacity is not None else None
            if not endpoint:
                suppressions.append("no_capacity")
                return "none"
            if cfg.dry_run:
                suppressions.append("dry_run")
                with self._lock:
                    self.intents_total += 1
                return "none"
            self._last_add_t = now
            self._action_times.append(now)
            with self._lock:
                self.adds_total += 1
            log.info("autoscale: adding engine %s", endpoint)
            self._submit(lambda: self.pool.add_engine(endpoint=endpoint,
                                                      wait=False))
            return "add"
        target = self._drain_target()
        if not target:
            suppressions.append("no_drain_target")
            return "none"
        if cfg.dry_run:
            suppressions.append("dry_run")
            with self._lock:
                self.intents_total += 1
            return "none"
        self._last_drain_t = now
        self._action_times.append(now)
        with self._lock:
            self.drains_total += 1
        log.info("autoscale: proactively draining %s", target)
        self._submit(lambda: self.pool.preempt(target))
        return "drain"

    def _drain_target(self) -> str | None:
        """Least-loaded ACTIVE remote engine, from the cached sweep (the
        tick's fleet counters just refreshed it). The colocated local
        engine is never a drain target — it is the degradation floor."""
        insts = self.pool.engines(refresh=False)
        cands = [i for i in insts
                 if i.get("active") and not i.get("is_local")]
        if not cands:
            return None
        cands.sort(key=lambda i: (int(i.get("num_running_reqs", 0)),
                                  float(i.get("occupancy", 0.0))))
        return cands[0].get("endpoint") or None

    def _submit(self, fn) -> None:
        self._inflight.set()
        if self._worker is None:
            self._worker = threading.Thread(target=self._exec_loop,
                                            name="autoscale-exec",
                                            daemon=True)
            self._worker.start()
        self._q.put(fn)

    def _exec_loop(self) -> None:
        while not self._closed.is_set():
            try:
                fn = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if fn is None:
                return
            try:
                fn()
            except Exception:  # noqa: BLE001 — a failed action is a
                # counter + log line, never a dead controller
                with self._lock:
                    self.exec_failures += 1
                log.exception("autoscale action failed")
            finally:
                if self._q.empty():
                    self._inflight.clear()

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until no action is executing (tests; returns False on
        timeout)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self._inflight.is_set():
                return True
            time.sleep(0.02)
        return not self._inflight.is_set()

    # -- degradation tiers -------------------------------------------------

    def _compute_tier(self) -> int:
        """Serving posture from the cached membership sweep: remote(0)
        while any remote engine is active, colocated(1) when only the
        local time-sliced engine is left, local(2) when nothing is — or
        when a ``finish_locally`` degraded completion happened since the
        last tick (the fleet may look recovered by the time the step
        record is cut; the tier transition must still be visible)."""
        insts = self.pool.engines(refresh=False)
        active = [i for i in insts if i.get("active")]
        if any(not i.get("is_local") for i in active):
            tier = 0
        elif active:
            tier = 1
        else:
            tier = 2
        if self.rollout is not None:
            fallbacks = int(getattr(self.rollout, "local_fallbacks", 0))
            if fallbacks > self._last_fallbacks:
                tier = 2
            self._last_fallbacks = fallbacks
        return tier

    def hold_admission(self) -> float:
        """Admission backpressure for the pipeline gate: block while the
        pool has ZERO active engines, up to ``admission_max_wait_s``.
        Always returns (never deadlocks) — past the deadline the stream
        proceeds and the ``finish_locally`` path degrades the batch.
        Returns the seconds waited."""
        cfg = self.cfg
        if not cfg.enabled or cfg.admission_max_wait_s <= 0:
            return 0.0
        t0 = self._clock()
        waited = 0.0
        while waited < cfg.admission_max_wait_s:
            try:
                if self.pool.active_count() > 0:
                    break
            except Exception:  # noqa: BLE001 — a mid-respawn manager
                break          # must not hold the gate shut
            if self._closed.wait(0.2):
                break
            waited = self._clock() - t0
        if waited:
            log.warning("autoscale admission gate held a stream %.2fs "
                        "(pool had zero active engines)", waited)
            with self._lock:
                self.gate_wait_s_total += waited
        return waited

    # -- /statusz ----------------------------------------------------------

    def statusz_section(self) -> dict:
        """The /statusz ``autoscale`` section: config echo, envelope,
        degradation tier, cumulative totals, and the last decision with
        its inputs and suppressions."""
        cfg = self.cfg
        with self._lock:
            return {
                "enabled": cfg.enabled,
                "dry_run": cfg.dry_run,
                "envelope": {"min": cfg.min_engines,
                             "max": cfg.max_engines},
                "degrade_tier": self.degrade_tier,
                "tier_name": TIERS[self.degrade_tier],
                "last": dict(self._last),
                "totals": {"ticks": self.ticks, "adds": self.adds_total,
                           "drains": self.drains_total,
                           "intents": self.intents_total,
                           "suppressed": self.suppressed_total,
                           "exec_failures": self.exec_failures,
                           "gate_wait_s": round(self.gate_wait_s_total, 3)},
            }
