"""Per-page KV ledger — the engine's memory plane (ARCHITECTURE.md "KV
memory plane").

The page pool was observed as two scalars (``page_util`` / peak, PR 7's
flight deck); every remaining memory feature — the host-RAM spill tier,
multi-turn suspended slots, SLO preemption — needs to know WHICH pages are
idle, who owns them, and how much HBM they really pin. The ledger answers
that with one record per physical page, maintained synchronously on the
engine loop thread at every page transition:

- **role** — ``free`` / ``active_decode`` (slot-owned) /
  ``prefix_cache_published`` (cache-owned, refcounted) /
  ``group_preref_held`` (published AND pinned by group-shared prefill
  pre-refs) / ``spilled`` (a LOGICAL role: the content lives in the host
  spill tier, rollout/kvspill.py, while the physical page is back on the
  free list); page 0 is the reserved null page and stays out of every
  count.
- **owner** — the rid (or group id) the page was allocated for.
- **birth / last-touch dispatch** — decode-dispatch ticks; each dispatch
  touches every page of every active slot's page row (the pages the
  attention kernels logically attend), so idle age = ticks since a decode
  last read the page.
- **free cause** — ``finalize`` / ``abort`` / ``salvage`` /
  ``cache_pressure`` / ``flush`` / ``preref_ttl``; page lifetime
  (free − birth) and idle-at-free age feed log2 histograms.

**Residency tiers**: a per-dispatch sweep buckets resident pages by idle
age — hot (< cold_after/4 dispatches), warm (< cold_after), cold
(>= cold_after, ``rollout.kv_cold_after_dispatches``). The cold set IS the
spill tier's candidate set: the engine's per-dispatch sweep pages cold
unreferenced published pages out to host RAM under watermark pressure
(``kv_spilled_frac`` / ``kv_restore_rate``, spill block in the statusz
``memory`` section).

**Reconciliation** (the flight-deck ``attributed_frac`` discipline): the
ledger's role counts must match the allocator free list + the prefix
cache's resident entries exactly whenever the engine is quiescent.
``memory/attributed_frac`` < 1.0 is transient mid-churn (e.g. flush-
orphaned entries whose pages free when their last holder releases);
a PERSISTENT deficit is a leak with a number attached.

**HBM truth** (:func:`hbm_truth`): per-device ``memory_stats()`` against
ledger-accounted bytes (KV pools + weights) — ``hbm_used_gb`` (max over
devices), ``hbm_headroom_gb`` (min over devices) and the unaccounted
residual, so a leak surfaces as a gauge, not an OOM. Empty on backends
that report no stats (CPU test runs).

Thread-safety: mutators run on the engine loop thread; readers
(``server_info`` / ``/statusz`` handler threads) take the same lock.
"""

from __future__ import annotations

import threading

import numpy as np

from polyrl_tpu.obs.histogram import Histogram

ROLE_FREE = 0
ROLE_ACTIVE = 1
ROLE_PUBLISHED = 2
ROLE_PREREF = 3
ROLE_RESERVED = 4  # page 0: the null page — never allocated, never counted

ROLE_NAMES = ("free", "active_decode", "prefix_cache_published",
              "group_preref_held")

# the "spilled" role is LOGICAL, not physical: a spilled page's content
# lives in host RAM (rollout/kvspill.py) while its physical page is back
# on the allocator free list — so it is tracked as a scalar count beside
# the physical role array, and role_counts() reports it as a fifth role

FREE_CAUSES = ("finalize", "abort", "salvage", "cache_pressure", "flush",
               "preref_ttl")

_GB = 1e9


def hbm_truth(accounted_bytes: float) -> dict:
    """Best-effort device-memory reconciliation: ``jax`` per-device
    ``memory_stats()`` vs the bytes the ledger can account for (KV pools +
    weights). Returns ``{}`` when no device reports stats (CPU test runs)
    — callers treat the keys as optional, like every per-field fleet
    aggregate."""
    try:
        import jax

        devs = jax.local_devices()
    except Exception:  # noqa: BLE001 — absent/uninitialized backend
        return {}
    used_max = 0.0
    headroom_min = None
    seen = False
    for d in devs:
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend without stats
            ms = None
        if not ms or "bytes_in_use" not in ms:
            continue
        seen = True
        used = float(ms["bytes_in_use"])
        used_max = max(used_max, used)
        limit = float(ms.get("bytes_limit", 0.0))
        if limit > 0.0:
            hr = (limit - used) / _GB
            headroom_min = hr if headroom_min is None else min(headroom_min,
                                                               hr)
    if not seen:
        return {}
    out = {
        "hbm_used_gb": used_max / _GB,
        # residual = device-reported use the ledger cannot attribute
        # (compiled executables, collectives scratch, a leak): a number
        # to watch instead of a surprise OOM
        "hbm_unaccounted_gb": max(0.0, used_max - float(accounted_bytes))
        / _GB,
    }
    if headroom_min is not None:
        out["hbm_headroom_gb"] = headroom_min
    return out


class PageLedger:
    """One record per physical KV page; see the module docstring. All
    page-id arguments are iterables of ints from the engine's allocator
    domain (1..num_pages-1)."""

    def __init__(self, num_pages: int, page_size: int,
                 cold_after_dispatches: int = 256):
        self.num_pages = int(num_pages)
        self.num_alloc_pages = self.num_pages - 1
        self.page_size = int(page_size)
        self.cold_after = max(1, int(cold_after_dispatches))
        self.warm_after = max(1, self.cold_after // 4)
        # per-page KV bytes; set by the engine once pools materialize
        self.page_bytes = 0
        self._lock = threading.Lock()
        self._role = np.zeros((self.num_pages,), np.uint8)
        self._role[0] = ROLE_RESERVED
        self._birth = np.zeros((self.num_pages,), np.int64)
        self._touch = np.zeros((self.num_pages,), np.int64)
        self._owner: list[str] = [""] * self.num_pages
        self.dispatch = 0  # monotone decode-dispatch tick
        # churn counters (cumulative)
        self.page_allocs = 0
        self.page_frees = 0
        self.page_publishes = 0
        self.freed_by_cause = {c: 0 for c in FREE_CAUSES}
        self.hists = {
            "page_lifetime_dispatches": Histogram(),  # free − birth
            "page_idle_age_dispatches": Histogram(),  # free − last touch
        }
        # last sweep (scalars; served without re-sweeping)
        self._tier_pages = {"hot": 0, "warm": 0, "cold": 0}
        # host-RAM spill tier (rollout/kvspill.py): page-count/byte truth.
        # spilled_pages is the CURRENT logical-spilled count (the "spilled"
        # role); the rest are cumulative. Reconciliation stays exact:
        # HBM-resident cache pages + spilled == prefix-cache entries.
        self.spilled_pages = 0
        self.pages_spilled = 0   # cumulative device→host
        self.pages_restored = 0  # cumulative host→device
        self.spill_drops = 0     # spilled content freed without restore
        self.spill_bytes = 0     # cumulative bytes device→host
        self.restore_bytes = 0   # cumulative bytes host→device
        # restore rate (pages/dispatch over a short window): the
        # spill-thrash signal the FlightRecorder watches — a HIGH rate
        # means restores chase the sweep (watermark hysteresis defeated)
        self.restore_rate = 0.0
        self._restore_marks: list[tuple[int, int]] = []

    # -- transitions (engine loop thread) ------------------------------------

    def on_alloc(self, pages, owner: str = "") -> None:
        """Pages left the allocator free list for a slot (active-decode)."""
        if not len(pages):
            return
        idx = np.asarray(pages, np.int64)
        with self._lock:
            self._role[idx] = ROLE_ACTIVE
            self._birth[idx] = self.dispatch
            self._touch[idx] = self.dispatch
            for p in idx.tolist():
                self._owner[p] = owner
            self.page_allocs += len(idx)

    def on_publish(self, pages) -> None:
        """Ownership moved slot → prefix cache (publish); only pages the
        ledger holds as active transition (a re-publish of an already
        cached page is a no-op, matching the cache's dedup)."""
        if not len(pages):
            return
        idx = np.asarray(list(pages), np.int64)
        with self._lock:
            sel = idx[self._role[idx] == ROLE_ACTIVE]
            self._role[sel] = ROLE_PUBLISHED
            self.page_publishes += len(sel)

    def on_preref_hold(self, pages) -> None:
        """Group-shared prefill pre-refs pinned these published pages."""
        if not len(pages):
            return
        idx = np.asarray(list(pages), np.int64)
        with self._lock:
            sel = idx[self._role[idx] == ROLE_PUBLISHED]
            self._role[sel] = ROLE_PREREF

    def on_preref_release(self, pages) -> None:
        """The group's pre-refs are gone (consumed / TTL-swept /
        disbanded): pinned pages fall back to plain published. Pages a
        release already freed (flush orphans) stay free — the guard on the
        current role makes the two orderings commute."""
        if not len(pages):
            return
        idx = np.asarray(list(pages), np.int64)
        with self._lock:
            sel = idx[self._role[idx] == ROLE_PREREF]
            self._role[sel] = ROLE_PUBLISHED

    def on_free(self, pages, cause: str) -> None:
        """Pages returned to the allocator free list; ``cause`` is one of
        :data:`FREE_CAUSES`."""
        if not len(pages):
            return
        idx = np.asarray(list(pages), np.int64)
        with self._lock:
            idx = idx[self._role[idx] != ROLE_FREE]  # double-free guard
            if not len(idx):
                return
            tick = self.dispatch
            self.hists["page_lifetime_dispatches"].observe_many(
                tick - self._birth[idx])
            self.hists["page_idle_age_dispatches"].observe_many(
                tick - self._touch[idx])
            self._role[idx] = ROLE_FREE
            for p in idx.tolist():
                self._owner[p] = ""
            n = len(idx)
            self.page_frees += n
            self.freed_by_cause[cause] = self.freed_by_cause.get(cause, 0) + n

    def on_spill(self, pages) -> None:
        """Published pages left HBM for the host spill tier: the physical
        pages are FREE again (the engine hands them to the allocator), the
        content moves to the logical ``spilled`` role. Not a free-cause —
        the KV survives, so lifetime/idle histograms stay untouched."""
        if not len(pages):
            return
        idx = np.asarray(list(pages), np.int64)
        with self._lock:
            sel = idx[self._role[idx] == ROLE_PUBLISHED]
            self._role[sel] = ROLE_FREE
            for p in sel.tolist():
                self._owner[p] = ""
            n = len(sel)
            self.spilled_pages += n
            self.pages_spilled += n
            self.spill_bytes += n * self.page_bytes

    def on_restore(self, pages) -> None:
        """Spilled content landed back in HBM at freshly allocated pages:
        they are cache-owned (published) immediately — a restore only ever
        happens for a prefix hit or a resuming chain about to attach."""
        if not len(pages):
            return
        idx = np.asarray(list(pages), np.int64)
        with self._lock:
            sel = idx[self._role[idx] == ROLE_FREE]
            self._role[sel] = ROLE_PUBLISHED
            self._birth[sel] = self.dispatch
            self._touch[sel] = self.dispatch
            n = len(sel)
            self.spilled_pages = max(0, self.spilled_pages - n)
            self.pages_restored += n
            self.restore_bytes += n * self.page_bytes

    def on_spill_drop(self, n: int) -> None:
        """Spilled content died without a restore (abort while spilled,
        cache flush, weight swap): both tiers are now free."""
        with self._lock:
            n = int(n)
            self.spilled_pages = max(0, self.spilled_pages - n)
            self.spill_drops += n

    def idle_age(self, page: int) -> int:
        """Dispatches since a decode last touched this resident page (the
        prefix cache's cold-first eviction order and the spill sweep's
        candidate ranking both key on it)."""
        with self._lock:
            return int(self.dispatch - self._touch[int(page)])

    def is_cold(self, page: int) -> bool:
        return self.idle_age(page) >= self.cold_after

    def on_dispatch(self, touched) -> None:
        """One decode dispatch: advance the tick, touch the pages the
        dispatch attends (every active slot's page row), and re-sweep the
        residency tiers. ``touched`` is an int array of page ids (page 0
        padding is tolerated — the reserved role keeps it out of every
        count)."""
        idx = np.asarray(touched, np.int64)
        with self._lock:
            self.dispatch += 1
            if len(idx):
                self._touch[idx] = self.dispatch
            resident = (self._role == ROLE_ACTIVE) \
                | (self._role == ROLE_PUBLISHED) \
                | (self._role == ROLE_PREREF)
            idle = self.dispatch - self._touch[resident]
            self._tier_pages = {
                "hot": int((idle < self.warm_after).sum()),
                "warm": int(((idle >= self.warm_after)
                             & (idle < self.cold_after)).sum()),
                "cold": int((idle >= self.cold_after).sum()),
            }
            # restore rate over the last ≤64 dispatches (pages/dispatch)
            self._restore_marks.append((self.dispatch, self.pages_restored))
            if len(self._restore_marks) > 64:
                self._restore_marks.pop(0)
            t0, r0 = self._restore_marks[0]
            span = self.dispatch - t0
            self.restore_rate = ((self.pages_restored - r0) / span
                                 if span > 0 else 0.0)

    # -- views ----------------------------------------------------------------

    def role_counts(self) -> dict[str, int]:
        with self._lock:
            return self._role_counts_locked()

    def _role_counts_locked(self) -> dict[str, int]:
        counts = np.bincount(self._role, minlength=5)
        out = {name: int(counts[i]) for i, name in enumerate(ROLE_NAMES)}
        # the logical fifth role: content in host RAM, physical page free
        out["spilled"] = int(self.spilled_pages)
        return out

    def attributed_frac(self, pool_free: int, cache_pages: int) -> float:
        """1.0 exactly when the ledger's role counts match the pool truth:
        ledger-free == allocator free-list length AND ledger cache-resident
        (published + preref-held) == prefix-cache entries. Transiently < 1
        mid-churn (flush orphans pending release); persistently < 1 = a
        missed transition = a leak with a number."""
        with self._lock:
            return self._attributed_locked(pool_free, cache_pages)

    def _attributed_locked(self, pool_free: int, cache_pages: int) -> float:
        c = self._role_counts_locked()
        # cache entries split across two tiers: HBM-resident (published /
        # preref-held physical pages) + spilled (content in host RAM) must
        # cover the prefix cache's entry count exactly
        mismatch = (abs(c["free"] - int(pool_free))
                    + abs(c["prefix_cache_published"]
                          + c["group_preref_held"] + c["spilled"]
                          - int(cache_pages)))
        return max(0.0, 1.0 - mismatch / max(1, self.num_alloc_pages))

    def server_info_fields(self, pool_free: int, cache_pages: int,
                           accounted_bytes: float) -> dict:
        """Flat fields merged into ``server_info`` (the manager's stats
        poller forwards ``kv_cold_page_frac`` / ``hbm_headroom_gb`` per
        instance; bench promotes both)."""
        with self._lock:
            n = max(1, self.num_alloc_pages)
            tiers = dict(self._tier_pages)
            fields = {
                "kv_hot_page_frac": round(tiers["hot"] / n, 6),
                "kv_warm_page_frac": round(tiers["warm"] / n, 6),
                "kv_cold_page_frac": round(tiers["cold"] / n, 6),
                "kv_cold_bytes": float(tiers["cold"] * self.page_bytes),
                # host-RAM spill tier (the manager forwards both per
                # instance; spilled_frac is relative to the HBM pool —
                # >1.0 legitimately means MORE KV lives on host than fits
                # on chip, the oversubscription win itself)
                "kv_spilled_frac": round(self.spilled_pages / n, 6),
                "kv_restore_rate": round(self.restore_rate, 6),
                "memory/attributed_frac": round(
                    self._attributed_locked(pool_free, cache_pages), 6),
                "memory/page_allocs": float(self.page_allocs),
                "memory/page_frees": float(self.page_frees),
                "memory/page_publishes": float(self.page_publishes),
                "memory/spilled_pages": float(self.spilled_pages),
                "memory/pages_spilled": float(self.pages_spilled),
                "memory/pages_restored": float(self.pages_restored),
                "memory/spill_drops": float(self.spill_drops),
                "memory/spill_bytes": float(self.spill_bytes),
                "memory/restore_bytes": float(self.restore_bytes),
            }
            for cause, count in self.freed_by_cause.items():
                fields[f"memory/freed_{cause}"] = float(count)
        fields.update(hbm_truth(accounted_bytes))
        return fields

    def snapshot(self, pool_free: int, cache_pages: int,
                 accounted_bytes: float) -> dict:
        """The ``/statusz`` ``memory`` section (nested, human-first)."""
        with self._lock:
            counts = self._role_counts_locked()
            owners: dict[str, int] = {}
            for p in range(1, self.num_pages):
                if self._role[p] in (ROLE_ACTIVE, ROLE_PREREF) \
                        and self._owner[p]:
                    owners[self._owner[p]] = owners.get(self._owner[p], 0) + 1
            top_owners = dict(sorted(owners.items(),
                                     key=lambda kv: -kv[1])[:8])
            out = {
                "roles": counts,
                "tiers": {
                    **{k: int(v) for k, v in self._tier_pages.items()},
                    "cold_bytes": float(self._tier_pages["cold"]
                                        * self.page_bytes),
                    "warm_after_dispatches": self.warm_after,
                    "cold_after_dispatches": self.cold_after,
                },
                "churn": {
                    "page_allocs": self.page_allocs,
                    "page_frees": self.page_frees,
                    "page_publishes": self.page_publishes,
                    "freed_by_cause": dict(self.freed_by_cause),
                },
                "reconcile": {
                    "attributed_frac": round(self._attributed_locked(
                        pool_free, cache_pages), 6),
                    "ledger_free": counts["free"],
                    "pool_free": int(pool_free),
                    # HBM-resident cache pages + spilled == cache entries
                    "ledger_cache": counts["prefix_cache_published"]
                    + counts["group_preref_held"] + counts["spilled"],
                    "cache_pages": int(cache_pages),
                },
                "spill": {
                    "spilled_pages": int(self.spilled_pages),
                    "spilled_bytes": int(self.spilled_pages
                                         * self.page_bytes),
                    "pages_spilled": int(self.pages_spilled),
                    "pages_restored": int(self.pages_restored),
                    "spill_drops": int(self.spill_drops),
                    "spill_bytes": int(self.spill_bytes),
                    "restore_bytes": int(self.restore_bytes),
                    "restore_rate": round(self.restore_rate, 6),
                },
                "hists": {name: {"p50": h.percentile(50.0),
                                 "p95": h.percentile(95.0),
                                 "p99": h.percentile(99.0),
                                 "max": h.vmax, "mean": h.mean,
                                 "count": h.count}
                          for name, h in self.hists.items() if h.count},
                "top_owners": top_owners,
                "dispatch": self.dispatch,
                "page_bytes": int(self.page_bytes),
                "accounted_bytes": float(accounted_bytes),
            }
        out["hbm"] = hbm_truth(accounted_bytes)
        return out
