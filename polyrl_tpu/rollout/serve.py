"""Rollout server launcher: ``python -m polyrl_tpu.rollout.serve``.

TPU-native equivalent of the reference's rollout-node launch path
(rlboost/sglang/launch_server.py:21-43 + patched_launch_server,
patches.py:513-543): build the engine, register with the rollout manager
(receiving the assigned weight-sender endpoint), spawn the weight-receiver
agent, then serve until shutdown.

The receiver's buffer layout is derived from THIS server's own model params
— the same scheme as the reference, where the TpWorker builds meta tensors
from its own model on bootstrap (patches.py:169-183); the sender validates
compatibility via the buffer-length handshake.
"""

from __future__ import annotations

import argparse
import logging
import os
import time

log = logging.getLogger(__name__)


def create_server(model: str, manager_endpoint: str | None = None,
                  host: str = "0.0.0.0", port: int = 0,
                  advertise_host: str = "127.0.0.1",
                  dtype: str = "bfloat16", seed: int = 0,
                  transfer_streams: int = 4,
                  batch_buckets: tuple[int, ...] | None = None,
                  prompt_buckets: tuple[int, ...] | None = None,
                  is_local: bool = False,
                  model_overrides: dict | None = None,
                  backend: str = "cb",
                  max_slots: int = 64,
                  page_size: int = 64,
                  max_seq_len: int = 16384,
                  num_pages: int | None = None,
                  steps_per_dispatch: int = 8,
                  pipeline_depth: int | None = None,
                  weight_quant: str = "",
                  warmup: bool = False,
                  tp: int = 1,
                  prefill_chunk: int = 0,
                  spec_tokens: int = 0,
                  spec_rounds: int = 2,
                  lora_rank: int = 0,
                  lora_alpha: float = 16.0,
                  salvage_partials: bool = True,
                  admit_wave: int | None = None,
                  admit_reorder_window: int = 8,
                  group_share: bool = True,
                  decode_group_share: bool = True,
                  group_preref_ttl_s: float | None = None,
                  kv_ledger: bool = True,
                  kv_cold_after_dispatches: int = 256,
                  kv_spill: bool = True,
                  kv_spill_host_gb: float = 4.0,
                  kv_spill_high_watermark: float = 0.92,
                  kv_spill_low_watermark: float = 0.80,
                  loop_profile: bool = True,
                  fault_injector=None):
    """Build engine + server, register with the manager, attach receiver.

    ``backend="cb"`` (default) serves with the paged continuous-batching
    engine; ``backend="step"`` keeps the bucketed v0 StepDecoder path.
    ``weight_quant="int8"`` serves with int8 weight-only quantized matmuls
    (models/quant.py) — halves weight HBM and fits 8B-class models on a
    16 GiB chip; weight pushes from the trainer stay bf16 on the wire and
    are re-quantized on arrival (server.weight_preprocess)."""
    import jax
    import jax.numpy as jnp

    from polyrl_tpu.models import decoder
    from polyrl_tpu.rollout.cb_engine import CBEngine
    from polyrl_tpu.rollout.engine import RolloutEngine
    from polyrl_tpu.rollout.server import RolloutServer

    if weight_quant not in ("", "int8"):
        raise ValueError(f"unknown weight_quant {weight_quant!r}")
    mesh = None
    if tp > 1:
        # tensor-parallel serving (the reference's --tp-size role,
        # launch_sglang.sh:13): params/KV shard over tp chips of this host.
        # Built BEFORE param materialization so weights never stage
        # unsharded through one chip's HBM (the models tp exists for don't
        # fit one chip).
        if backend != "cb":
            raise NotImplementedError("tp > 1 requires backend='cb'")
        from polyrl_tpu.parallel import mesh as meshlib

        devs = jax.devices()
        if len(devs) % tp != 0:
            raise ValueError(f"tp={tp} does not divide {len(devs)} devices")
        mesh = meshlib.make_mesh(meshlib.MeshConfig(fsdp=1, tp=tp),
                                 devs[:tp])
    if os.path.isdir(model):
        # a local HF checkpoint dir: pretrained weights + config.json arch.
        # With int8, the loader quantizes host-side — the full-precision
        # tree never exists on device (8B on a 16 GiB chip). Under tp the
        # leaves stay host-side and the engine device_puts each one
        # straight into its sharded layout.
        from polyrl_tpu.models.hf_loader import build_from_hf

        cfg, params = build_from_hf(model, dtype=getattr(jnp, dtype),
                                    overrides=model_overrides,
                                    quantize=weight_quant,
                                    to_device=mesh is None)
    else:
        cfg = decoder.get_config(model, dtype=getattr(jnp, dtype),
                                 **(model_overrides or {}))
        if weight_quant == "int8":
            from polyrl_tpu.models.quant import init_quantized_params

            # leaf-by-leaf device init in quantized form (same draws as
            # init_params; the bf16 tree never materializes)
            params = init_quantized_params(jax.random.PRNGKey(seed), cfg)
        elif mesh is not None:
            # born sharded: out_shardings places each leaf across tp at
            # init, no single-chip staging of the full tree
            from jax.sharding import NamedSharding, PartitionSpec as P

            specs = decoder.param_specs(cfg)
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P))
            params = jax.jit(
                lambda: decoder.init_params(jax.random.PRNGKey(seed), cfg),
                out_shardings=shardings)()
        else:
            params = jax.jit(
                lambda: decoder.init_params(jax.random.PRNGKey(seed), cfg))()
    weight_template = None
    weight_preprocess = None
    weight_apply = None
    if weight_quant == "int8" and lora_rank == 0:
        from polyrl_tpu.models.quant import quantize_params

        # the transfer fabric's layout/unflatten contract stays the
        # full-precision tree the TRAINER packs; quantize on arrival
        weight_template = jax.eval_shape(
            lambda: decoder.init_params(jax.random.PRNGKey(seed), cfg))
        weight_preprocess = quantize_params
    if lora_rank > 0:
        # LoRA DELTA sync (trainer.weight_sync=lora_delta): serve the
        # wrapped tree — the base (possibly int8 ⇒ QLoRA serving) never
        # changes, and each push carries only the a/b adapters (~rank/
        # hidden of the full tree), replacing them in place. The trainer
        # must run the same lora_rank/alpha.
        from polyrl_tpu.models import lora as lora_mod

        params = lora_mod.wrap_lora(params,
                                    jax.random.PRNGKey(7919 + lora_rank),
                                    lora_rank, lora_alpha)
        weight_template = lora_mod.adapter_template(cfg, lora_rank)
        weight_preprocess = None
        weight_apply = lora_mod.apply_adapters
    if backend == "cb":
        engine = CBEngine(
            cfg, params, pad_token_id=0, kv_cache_dtype=getattr(jnp, dtype),
            max_slots=max_slots, page_size=page_size, max_seq_len=max_seq_len,
            num_pages=num_pages, steps_per_dispatch=steps_per_dispatch,
            prompt_buckets=tuple(prompt_buckets) if prompt_buckets
            else (128, 256, 512, 1024, 2048, 4096), seed=seed, mesh=mesh,
            prefill_chunk=prefill_chunk, spec_tokens=spec_tokens,
            spec_rounds=spec_rounds, pipeline_depth=pipeline_depth,
            salvage_partials=salvage_partials, admit_wave=admit_wave,
            admit_reorder_window=admit_reorder_window,
            group_share=group_share, decode_group_share=decode_group_share,
            group_preref_ttl_s=group_preref_ttl_s,
            kv_ledger=kv_ledger,
            kv_cold_after_dispatches=kv_cold_after_dispatches,
            kv_spill=kv_spill,
            kv_spill_host_gb=kv_spill_host_gb,
            kv_spill_high_watermark=kv_spill_high_watermark,
            kv_spill_low_watermark=kv_spill_low_watermark,
            loop_profile=loop_profile)
    else:
        kwargs = {}
        if batch_buckets:
            kwargs["batch_buckets"] = tuple(batch_buckets)
        if prompt_buckets:
            kwargs["prompt_buckets"] = tuple(prompt_buckets)
        engine = RolloutEngine(cfg, params, pad_token_id=0,
                               kv_cache_dtype=getattr(jnp, dtype), **kwargs)
    if warmup and backend == "cb":
        # precompile every admission/decode bucket before the manager's
        # health check promotes this instance (the reference leans on
        # SGLang's own server warmup; here it's a first-class engine step)
        engine.warmup()
    server = RolloutServer(engine, host=host, port=port,
                           advertise_host=advertise_host)
    server.weight_template = weight_template
    server.weight_preprocess = weight_preprocess
    server.weight_apply = weight_apply
    server.fault = fault_injector
    server.start()

    if manager_endpoint:
        register_with_manager(server, manager_endpoint, is_local=is_local,
                              transfer_streams=transfer_streams)
    return server


def register_with_manager(server, manager_endpoint: str = "",
                          is_local: bool = False,
                          transfer_streams: int = 4,
                          client=None) -> None:
    """POST /register_rollout_instance; spawn the receiver agent pointed at
    the assigned weight sender (reference §3.2 startup flow). Passing an
    existing ``client`` (PoolManager.add_engine does) registers through it
    so a bound supervisor records the membership for /reconcile replay."""
    from polyrl_tpu.manager.client import ManagerClient
    from polyrl_tpu.transfer.agents import ReceiverAgent
    from polyrl_tpu.transfer.layout import build_layout, build_shard_spec

    if client is None:
        if not manager_endpoint:
            raise ValueError("register_with_manager needs an endpoint or "
                             "a client")
        client = ManagerClient(manager_endpoint)
    # remember who we joined: the /preempt → leave() lifecycle deregisters
    # through this endpoint on graceful departure
    server.manager_endpoint = client.endpoint.replace("http://", "")
    if is_local:
        client.register_local_rollout_instances([server.endpoint])
        return
    out = client.register_rollout_instance(server.endpoint)
    sender_ep = out.get("weight_sender_endpoint") or ""
    if sender_ep:
        # quantized engines keep the TRAINER's bf16 tree as the wire layout
        layout = build_layout(server.weight_template
                              if server.weight_template is not None
                              else server.engine.params)
        # advertise THIS engine's tp sharding so the sender builds the
        # (trainer shard → engine shard) resharding map per receiver.
        # Quantized/LoRA wire templates are host trees — they come back
        # replicated, which correctly disables the sharded plan for them.
        shard_spec = build_shard_spec(server.weight_template
                                      if server.weight_template is not None
                                      else server.engine.params, axis="tp")
        advertise = server.endpoint.rsplit(":", 1)[0]
        server.receiver = ReceiverAgent(
            layout, server.endpoint, sender_ep,
            num_streams=transfer_streams, advertise_host=advertise,
            shard_spec=shard_spec)
        server.receiver.start()
        log.info("receiver agent attached to sender %s", sender_ep)


def main() -> None:
    p = argparse.ArgumentParser(description="polyrl-tpu rollout server")
    p.add_argument("--model", default="qwen3-1.7b")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=30000)
    p.add_argument("--advertise-host", default="127.0.0.1")
    p.add_argument("--manager-endpoint", default=None,
                   help="host:port of the rollout manager")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--is-local", action="store_true",
                   help="register as a colocated (time-sliced) instance")
    p.add_argument("--transfer-streams", type=int, default=4)
    p.add_argument("--backend", default="cb", choices=("cb", "step"),
                   help="cb = paged continuous batching, step = bucketed v0")
    p.add_argument("--max-slots", type=int, default=64)
    p.add_argument("--page-size", type=int, default=64)
    p.add_argument("--max-seq-len", type=int, default=16384)
    p.add_argument("--steps-per-dispatch", type=int, default=8,
                   help="fused decode steps per device dispatch")
    p.add_argument("--pipeline-depth", type=int, default=None,
                   help="run-ahead dispatch window for the fetcher-thread "
                        "pipeline (default 16 / POLYRL_CB_PIPELINE); lower "
                        "it for tighter abort latency on colocated "
                        "time-sliced workers")
    p.add_argument("--weight-quant", default="", choices=("", "int8"),
                   help="int8 = weight-only quantized serving")
    p.add_argument("--warmup", action="store_true",
                   help="precompile all admission/decode buckets at launch")
    p.add_argument("--prompt-buckets", type=int, nargs="+", default=None,
                   help="prompt-length padding buckets (default "
                        "128 256 512 1024 2048 4096)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel serving over this many chips")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="chunked prefill: prompts longer than this prefill "
                        "one page-aligned chunk per engine iteration, "
                        "interleaved with decode (0 = off)")
    p.add_argument("--spec-tokens", type=int, default=0,
                   help="prompt-lookup speculative decoding: verify this "
                        "many ngram-proposed draft tokens per decode "
                        "dispatch — up to N+1 tokens per weight read, "
                        "distribution-exact (0 = off)")
    p.add_argument("--spec-rounds", type=int, default=2,
                   help="fused device-side speculation rounds per dispatch "
                        "(proposals and acceptance never leave the chip)")
    p.add_argument("--admit-wave", type=int, default=None,
                   help="max admissions fused into one batched prefill "
                        "dispatch (default 8)")
    p.add_argument("--admit-reorder-window", type=int, default=8,
                   help="blocked queue heads admission may skip past while "
                        "forming a wave (0 = strict FIFO head-of-line)")
    p.add_argument("--no-group-share", action="store_true",
                   help="disable group-shared prefill (siblings admit as "
                        "singleton suffix dispatches — the A/B baseline)")
    p.add_argument("--no-decode-group-share", action="store_true",
                   help="disable shared-prefix decode attention (every "
                        "sibling re-streams the group's prompt KV per "
                        "decode step — the --decode-attn A/B baseline)")
    p.add_argument("--group-preref-ttl-s", type=float, default=None,
                   help="sibling-wait pre-ref expiry for groups whose "
                        "members never arrive (default 30)")
    p.add_argument("--no-kv-ledger", action="store_true",
                   help="disable the per-page KV memory ledger (the "
                        "memory statusz section / kv_*_page_frac gauges "
                        "go empty; engine output is identical either way)")
    p.add_argument("--kv-cold-after-dispatches", type=int, default=256,
                   help="idle age (decode dispatches) past which a "
                        "resident KV page counts as cold")
    p.add_argument("--no-kv-spill", action="store_true",
                   help="disable the host-RAM KV spill tier (cold "
                        "published pages stay in HBM and capacity "
                        "eviction destroys them; --no-kv-ledger also "
                        "disables spilling)")
    p.add_argument("--kv-spill-host-gb", type=float, default=4.0,
                   help="host-side capacity of the KV spill tier, GB")
    p.add_argument("--no-loop-profile", action="store_true",
                   help="disable the engine-loop profiler (the engine.loop "
                        "statusz block reads enabled=false and the "
                        "device_frac/accounting_frac gauges go absent; "
                        "sampled output is identical either way)")
    p.add_argument("--lora-rank", type=int, default=0,
                   help="LoRA delta sync: serve base + adapters; pushes "
                        "carry only adapters (match the trainer's rank)")
    p.add_argument("--lora-alpha", type=float, default=16.0)
    p.add_argument("--seed", type=int, default=0,
                   help="random-init seed for preset models (delta sync "
                        "presumes trainer and workers share the base — "
                        "normally via the same checkpoint dir)")
    args = p.parse_args()

    logging.basicConfig(level=logging.INFO)
    server = create_server(args.model, args.manager_endpoint, host=args.host,
                           port=args.port, advertise_host=args.advertise_host,
                           dtype=args.dtype, is_local=args.is_local,
                           seed=args.seed,
                           transfer_streams=args.transfer_streams,
                           backend=args.backend, max_slots=args.max_slots,
                           page_size=args.page_size,
                           max_seq_len=args.max_seq_len,
                           steps_per_dispatch=args.steps_per_dispatch,
                           pipeline_depth=args.pipeline_depth,
                           weight_quant=args.weight_quant,
                           warmup=args.warmup,
                           prompt_buckets=args.prompt_buckets,
                           tp=args.tp,
                           prefill_chunk=args.prefill_chunk,
                           spec_tokens=args.spec_tokens,
                           spec_rounds=args.spec_rounds,
                           admit_wave=args.admit_wave,
                           admit_reorder_window=args.admit_reorder_window,
                           group_share=not args.no_group_share,
                           decode_group_share=not args.no_decode_group_share,
                           group_preref_ttl_s=args.group_preref_ttl_s,
                           kv_ledger=not args.no_kv_ledger,
                           kv_cold_after_dispatches=(
                               args.kv_cold_after_dispatches),
                           kv_spill=not args.no_kv_spill,
                           kv_spill_host_gb=args.kv_spill_host_gb,
                           loop_profile=not args.no_loop_profile,
                           lora_rank=args.lora_rank,
                           lora_alpha=args.lora_alpha)
    log.info("rollout server on %s", server.endpoint)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
