"""Trace-driven spot-market chaos harness (ARCHITECTURE.md "Closed-loop
autoscaling & degradation tiers": spot-trace format).

The paper's capacity story assumes rollout engines live on HARVESTED
spot capacity: instances arrive when the market has surplus, leave with
a short preemption notice, and sometimes just die. This module replays a
scripted ``(t, event)`` schedule against the elastic pool so the whole
closed loop — AutoscaleController adds from offers, PoolManager drains
on notices, heartbeat eviction + token-level continuation on kills — is
drillable deterministically in tests and ``bench.py --pool --spot-trace
FILE``:

- ``offer``  — capacity appears. The market starts an engine via its
  ``engine_factory`` (or takes the event's pre-existing ``endpoint``)
  and queues it for :meth:`acquire` — the controller's next add decision
  picks it up. ``auto_add: true`` joins the pool directly instead (the
  market forcing capacity ON — how a drill pushes the fleet ABOVE the
  envelope to provoke a proactive drain).
- ``notice`` — preemption WITH a grace window (the ~2-min spot warning,
  compressed): ``PoolManager.preempt`` drains the engine so in-flight
  tokens ride the salvage path (abort partials → suffix resumes on
  survivors) instead of dying with the instance.
- ``kill``   — preemption WITHOUT notice (SIGKILL semantics): streams
  break mid-line, recovery is heartbeat eviction + manager continuation.

Trace format (JSONL, one event per line; ``#`` comments and blank lines
skipped)::

    {"t": 1.0, "event": "offer",  "name": "C"}
    {"t": 1.0, "event": "notice", "target": "A"}
    {"t": 3.0, "event": "kill",   "target": "B"}
    {"t": 7.0, "event": "offer",  "name": "F", "auto_add": true}

``t`` is seconds from :meth:`start` (scaled by ``time_scale``) with the
default ``time_base="wall"``; with ``time_base="step"`` events fire
synchronously from the controller's tick when the trainer step reaches
``t`` — the deterministic pacing the chaos e2e uses. ``target`` names an
engine the market knows: a prior offer's ``name``, or one registered
via :meth:`adopt`. Counters ride the step record through the
fault-injection plane: attach via ``FaultInjector``'s ``spot`` hook and
``fault/spot_{offers,notices,kills}`` land next to the ``fault/*``
recovery counters they cause.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import queue
import threading
import time

from polyrl_tpu.rollout.autoscale import CapacityProvider

log = logging.getLogger(__name__)

_EVENTS = ("offer", "notice", "kill")


@dataclasses.dataclass
class SpotMarketConfig:
    """``rollout.spot_market.*`` knobs (config.py RolloutSection),
    mirroring the ``transfer.fault_injection`` config idiom: a dataclass
    the run config owns, default OFF."""
    enabled: bool = False
    # JSONL schedule (see module docstring); "" with no inline events =
    # an empty market (acquire always returns None)
    trace_path: str = ""
    # notice grace window: how long preempt waits for abort partials to
    # flush before deregistering (compressed from spot's ~2 minutes)
    grace_s: float = 0.5
    # wall-mode time compression: event fires at t * time_scale
    time_scale: float = 1.0
    # "wall" replays on a background thread against the clock; "step"
    # fires events from AutoscaleController.tick when the trainer step
    # reaches t (deterministic — the chaos e2e's pacing)
    time_base: str = "wall"


def load_trace(path: str) -> list[dict]:
    """Parse a JSONL trace file; validates event kinds and sorts by t
    (stable, so same-t events keep file order)."""
    events: list[dict] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            ev = json.loads(line)
            kind = ev.get("event")
            if kind not in _EVENTS:
                raise ValueError(
                    f"{path}:{lineno}: unknown spot event {kind!r} "
                    f"(expected one of {_EVENTS})")
            ev["t"] = float(ev.get("t", 0.0))
            events.append(ev)
    events.sort(key=lambda e: e["t"])
    return events


class SpotMarket(CapacityProvider):
    """Replays a spot trace against a :class:`PoolManager`; doubles as
    the controller's :class:`CapacityProvider` (offers queue for
    :meth:`acquire`). ``engine_factory`` is a zero-arg callable
    returning a started engine handle (``.endpoint``, ``.kill()``,
    ``.stop()``) — tests pass a FakeEngine builder, bench a real
    CBEngine server builder; offers carrying an explicit ``endpoint``
    need no factory. Attaching ``injector`` (a rollout FaultInjector)
    merges ``fault/spot_*`` counters into every step record."""

    def __init__(self, pool, cfg: SpotMarketConfig | None = None,
                 engine_factory=None, injector=None,
                 events: list[dict] | None = None):
        self.pool = pool
        self.cfg = cfg or SpotMarketConfig(enabled=True)
        self.engine_factory = engine_factory
        if events is None:
            events = (load_trace(self.cfg.trace_path)
                      if self.cfg.trace_path else [])
        self._events = sorted(list(events), key=lambda e: float(e.get("t", 0.0)))
        self._idx = 0                      # step-mode replay cursor
        self._handles: dict[str, object] = {}   # name -> engine handle
        self._owned: list[object] = []     # handles the market must stop
        self._ready: queue.Queue = queue.Queue()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # all events fired (bench waits on this before measuring recovery)
        self.done = threading.Event()
        if not self._events:
            self.done.set()
        # cumulative counters (public, like every injector in faults.py)
        self.offers = 0
        self.notices = 0
        self.kills = 0
        # wall timestamp of the first disruptive event (notice/kill) —
        # the bench's recovery_s clock starts here
        self.first_disruption_t: float | None = None
        if injector is not None:
            injector.spot = self

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SpotMarket":
        """Arm the market. Wall mode spawns the replay thread; step mode
        is passive — events fire from :meth:`on_step`."""
        if self.cfg.time_base == "wall" and self._events:
            self._thread = threading.Thread(target=self._replay,
                                            name="spot-market", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        for handle in self._owned:
            try:
                handle.stop()
            except Exception:  # noqa: BLE001 — killed engines are down
                pass

    def _replay(self) -> None:
        t0 = time.monotonic()
        for ev in self._events:
            delay = (ev["t"] * self.cfg.time_scale
                     - (time.monotonic() - t0))
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            self._fire(ev, sync=False)
        self.done.set()

    def on_step(self, step: int) -> int:
        """Step-paced replay (``time_base="step"``): fire every event
        with ``t <= step``, synchronously — by the time the controller
        decides, the pool reflects the market. Returns events fired."""
        if self.cfg.time_base != "step":
            return 0
        fired = 0
        while (self._idx < len(self._events)
               and self._events[self._idx]["t"] <= step):
            self._fire(self._events[self._idx], sync=True)
            self._idx += 1
            fired += 1
        if self._idx >= len(self._events):
            self.done.set()
        return fired

    # -- CapacityProvider --------------------------------------------------

    def acquire(self) -> str | None:
        try:
            return self._ready.get_nowait()
        except queue.Empty:
            return None

    # -- event dispatch ----------------------------------------------------

    def adopt(self, name: str, handle) -> None:
        """Register a pre-existing engine under a trace name so notices/
        kills can target it (the market does NOT own it: :meth:`stop`
        leaves it running)."""
        with self._lock:
            self._handles[str(name)] = handle

    def _fire(self, ev: dict, sync: bool) -> None:
        try:
            kind = ev.get("event")
            log.info("spot market: %s %s", kind,
                     ev.get("name") or ev.get("target") or "")
            if kind == "offer":
                self._offer(ev)
            elif kind == "notice":
                self._notice(ev, sync)
            elif kind == "kill":
                self._kill(ev)
        except Exception:  # noqa: BLE001 — a failed event is a log
            # line, not a dead market (the drill must keep replaying)
            log.exception("spot event failed: %r", ev)

    def _offer(self, ev: dict) -> None:
        endpoint = str(ev.get("endpoint", ""))
        handle = None
        if not endpoint:
            if self.engine_factory is None:
                log.warning("spot offer without endpoint and no "
                            "engine_factory; dropped: %r", ev)
                return
            handle = self.engine_factory()
            endpoint = handle.endpoint
        name = str(ev.get("name") or endpoint)
        with self._lock:
            self.offers += 1
            if handle is not None:
                self._handles[name] = handle
                self._owned.append(handle)
        if ev.get("auto_add"):
            # market forces capacity on (no controller decision): the
            # over-the-envelope drill provoking a proactive drain
            self.pool.add_engine(endpoint=endpoint, wait=False)
        else:
            self._ready.put(endpoint)

    def _resolve(self, ev: dict):
        name = str(ev.get("target") or ev.get("name") or "")
        with self._lock:
            handle = self._handles.get(name)
        endpoint = str(ev.get("endpoint", "")) or (
            handle.endpoint if handle is not None else "")
        return handle, endpoint

    def _notice(self, ev: dict, sync: bool) -> None:
        handle, endpoint = self._resolve(ev)
        if not endpoint:
            log.warning("spot notice with no resolvable target: %r", ev)
            return
        with self._lock:
            self.notices += 1
            self._mark_disruption()

        def run() -> None:
            # the grace-window warning: drain so in-flight tokens ride
            # the salvage path, then the instance actually goes away
            self.pool.preempt(endpoint, grace_s=self.cfg.grace_s)
            if handle is not None and ev.get("terminate", True):
                handle.kill()

        if sync:
            run()
        else:
            # wall mode: preempt sleeps out the grace window — off the
            # replay thread so later events stay on schedule
            threading.Thread(target=run, name="spot-notice",
                             daemon=True).start()

    def _kill(self, ev: dict) -> None:
        handle, endpoint = self._resolve(ev)
        if handle is None or not hasattr(handle, "kill"):
            log.warning("spot kill needs an owned/adopted handle: %r", ev)
            return
        with self._lock:
            self.kills += 1
            self._mark_disruption()
        handle.kill()

    def _mark_disruption(self) -> None:
        if self.first_disruption_t is None:
            self.first_disruption_t = time.monotonic()

    # -- telemetry ---------------------------------------------------------

    def counters(self) -> dict[str, float]:
        return {
            "fault/spot_offers": float(self.offers),
            "fault/spot_notices": float(self.notices),
            "fault/spot_kills": float(self.kills),
        }
